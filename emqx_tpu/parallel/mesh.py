"""Mesh construction helpers."""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh


def make_mesh(n_devices: Optional[int] = None, dp: Optional[int] = None,
              route: Optional[int] = None,
              devices: Optional[Sequence] = None) -> Mesh:
    """Build a ('dp', 'route') mesh over the available devices.

    Default split: all devices on 'route' (filter sharding) — the match NFA
    is gather-bound, so partitioning the trie buys the most HBM headroom;
    raise `dp` to shard the publish batch too.
    """
    devs = list(devices if devices is not None else jax.devices())
    n = n_devices or len(devs)
    devs = devs[:n]
    if dp is None and route is None:
        dp, route = 1, n
    elif dp is None:
        dp = n // route
    elif route is None:
        route = n // dp
    if dp * route != n:
        raise ValueError(f"dp({dp}) * route({route}) != n_devices({n})")
    return Mesh(np.asarray(devs).reshape(dp, route), ("dp", "route"))
