"""Mesh + shard_map parallelism for the route engine.

Mapping of the reference's distribution mechanisms (SURVEY.md §2.4) onto TPU
mesh axes: filter space is sharded over the 'route' axis (each device holds a
sub-trie of its filter subset — the analog of emqx's fully-replicated route
table being read-locally, P4, but partitioned instead of replicated because
HBM is the budget); publish batches shard over 'dp' (the {active,N} batching
window, P10); intra-slice combination rides ICI via all_gather/psum instead
of gen_rpc TCP channels (P6).
"""
