"""shard_map'ed route step: filter-sharded trie × batch-sharded publishes.

Each 'route' shard owns a disjoint filter subset compiled into its own
RouterTables (same array shapes, different contents — stacked on a leading
axis). Publish batches shard over 'dp'. One step computes every (dp, route)
pair's local matches/fan-out; shared-subscription round-robin cursors stay
consistent across 'dp' shards by all-gathering per-slot occurrence counts
and rebasing each shard's cursor offset by the occurrences of lower dp ranks
(deterministic global batch order), then psum-advancing.

This is the ICI data plane replacing the reference's gen_rpc cross-node
forwarding (emqx_rpc.erl:20-60): instead of shipping messages to the node
that owns the route, every shard matches its slice and results ride the
interconnect (SURVEY.md §2.4 P6, §5.8).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

from emqx_tpu.models.router_engine import (ExchangeResult, RouterTables,
                                           RouteResult)
from emqx_tpu.ops.fanout import fanout_normal, shared_slots
from emqx_tpu.ops.match import match_batch
from emqx_tpu.ops.pallas_exchange import exchange_rotate_impl, ring_rotate
from emqx_tpu.ops.shapes import shape_match
from emqx_tpu.ops.shared import STRATEGY_ROUND_ROBIN, pick_members


def stack_tables(tables_list: list) -> RouterTables:
    """Stack per-shard RouterTables on a new leading axis (host, numpy).

    All shards must share array shapes — build each with the same
    node/slot/filter capacities (the host router buckets capacities pow2).
    """
    return jax.tree.map(lambda *xs: np.stack(xs), *tables_list)


def put_sharded(mesh: Mesh, tables_stacked: RouterTables, cursors_stacked,
                ledger=None):
    """Place stacked tables/cursors with their 'route' sharding.

    `ledger` (broker.hbm_ledger.HbmLedger, ISSUE 8): when given, the
    placed pytrees register as the mesh_tables / mesh_cursors
    categories so the shard tables stop being unaccounted HBM."""
    spec = NamedSharding(mesh, P("route"))
    # hbm: held right below under mesh_tables / mesh_cursors
    tables = jax.tree.map(lambda x: jax.device_put(x, spec), tables_stacked)
    cursors = jax.device_put(cursors_stacked, spec)  # hbm: held below
    if ledger is not None:
        tables = ledger.hold("mesh_tables", tables)
        cursors = ledger.hold("mesh_cursors", cursors)
    return tables, cursors


@functools.partial(jax.jit, donate_argnums=0)
def _apply_shard_update(full, new, idx):
    """Write one shard's tables into the stacked device arrays in place
    (donated buffers; the traced index keeps ONE compilation for all
    shards). Under a 'route' sharding XLA updates only the owning
    device's slice — nothing else moves."""
    return jax.tree.map(
        lambda f, n: jax.lax.dynamic_update_index_in_dim(f, n, idx, 0),
        full, new)


@jax.jit
def _apply_shard_update_keep(full, new, idx):
    """Non-donating variant: the PREVIOUS stacked tables stay valid —
    required when in-flight consumers (pipelined serving handles, a warm
    thread) still hold the old pytree. Costs a transient second copy of
    the updated arrays."""
    return jax.tree.map(
        lambda f, n: jax.lax.dynamic_update_index_in_dim(f, n, idx, 0),
        full, new)


def update_shard(tables_stacked, shard_idx: int, shard_tables,
                 donate: bool = True):
    """Incremental churn path (SURVEY §7 hard-part 1 under the mesh):
    subscription changes in ONE filter shard rebuild that shard host-side
    (same capacities as its siblings) and re-put ONLY its slice — the
    round-1 story (rebuild one shard -> restack -> re-upload everything)
    is gone.

    tables_stacked: device pytree with leading 'route' axis (donated
    unless donate=False — pass False whenever anything else may still
    read the old arrays).
    shard_tables: the ONE shard's host pytree (no leading axis).
    Returns the updated stacked pytree; the caller must adopt it (with
    donate=True the donated input is invalid afterwards).
    """
    n_shards = jax.tree.leaves(tables_stacked)[0].shape[0]
    if not 0 <= shard_idx < n_shards:
        # dynamic_update_index_in_dim would silently CLAMP and corrupt
        # the edge shard
        raise IndexError(f"shard_idx {shard_idx} out of range "
                         f"[0, {n_shards})")
    shapes_ok = jax.tree.map(
        lambda f, n: f.shape[1:] == n.shape, tables_stacked, shard_tables)
    if not all(jax.tree.leaves(shapes_ok)):
        raise ValueError(
            "shard tables shapes diverge from the stacked capacity "
            "classes; rebuild every shard with matching capacities")
    apply = _apply_shard_update if donate else _apply_shard_update_keep
    return apply(tables_stacked, shard_tables, jnp.int32(shard_idx))


def make_sharded_route_step(mesh: Mesh, *, backend: str = "trie",
                            frontier_cap: int = 16,
                            match_cap: int = 64, fanout_cap: int = 128,
                            slot_cap: int = 16):
    """Build the jitted multi-device route step for `mesh` ('dp','route').

    backend: 'trie' (RouterTables shards) or 'shapes' (ShapeRouterTables
    shards — the fast path).

    Call signature of the returned fn:
      step(tables [R,...], cursors [R,G], topics [B,L], lens [B],
           is_dollar [B], msg_hash [B], strategy scalar) -> RouteResult
    where per-topic outputs come back as [B, R, ...] (R = route shards,
    local filter ids per shard) and cursors as [R, G].
    """
    dp_size = mesh.shape["dp"]

    def local_step(tables, cursors, topics, lens, is_dollar, msg_hash,
                   strategy):
        tables = jax.tree.map(lambda x: x[0], tables)  # this shard's slice
        cursors = cursors[0]

        if backend == "shapes":
            mr = shape_match(tables.shapes, topics, lens, is_dollar)
        else:
            mr = match_batch(tables.trie, topics, lens, is_dollar,
                             frontier_cap=frontier_cap, match_cap=match_cap)
        fr = fanout_normal(tables.subs, mr.matches, fanout_cap=fanout_cap)
        sids, slot_oflow = shared_slots(tables.subs, mr.matches,
                                        slot_cap=slot_cap)

        # cross-dp deterministic round-robin: rebase cursors by the
        # occurrences seen in lower dp ranks, advance by the global total
        occur_local = jnp.zeros_like(cursors).at[
            jnp.clip(sids, 0).reshape(-1)].add(
            (sids >= 0).reshape(-1).astype(cursors.dtype))
        occur_all = jax.lax.all_gather(occur_local, "dp")        # [dp, G]
        my_dp = jax.lax.axis_index("dp")
        prefix = jnp.sum(jnp.where(
            jnp.arange(dp_size)[:, None] < my_dp, occur_all, 0), axis=0)
        is_rr = strategy == STRATEGY_ROUND_ROBIN
        sp = pick_members(tables.subs, cursors + jnp.where(is_rr, prefix, 0),
                          sids, strategy, msg_hash)
        total_occur = occur_all.sum(axis=0)
        new_cursors = jnp.where(is_rr, cursors + total_occur, cursors)

        overflow = mr.overflow | fr.overflow | slot_oflow
        res = RouteResult(
            matches=mr.matches, match_counts=mr.counts,
            rows=fr.rows, opts=fr.opts, fan_counts=fr.counts,
            shared_sids=sids, shared_rows=sp.rows, shared_opts=sp.opts,
            overflow=overflow, new_cursors=new_cursors, occur=total_occur)
        # per-topic outputs gain a 'route' axis at dim 1; cursor state keeps
        # its leading 'route' axis
        return RouteResult(
            matches=res.matches[:, None], match_counts=res.match_counts[:, None],
            rows=res.rows[:, None], opts=res.opts[:, None],
            fan_counts=res.fan_counts[:, None],
            shared_sids=res.shared_sids[:, None],
            shared_rows=res.shared_rows[:, None],
            shared_opts=res.shared_opts[:, None],
            overflow=res.overflow[:, None],
            new_cursors=res.new_cursors[None], occur=res.occur[None])

    table_spec = P("route")
    per_topic_spec = P("dp", "route")
    out_specs = RouteResult(
        matches=per_topic_spec, match_counts=per_topic_spec,
        rows=per_topic_spec, opts=per_topic_spec, fan_counts=per_topic_spec,
        shared_sids=per_topic_spec, shared_rows=per_topic_spec,
        shared_opts=per_topic_spec,
        overflow=per_topic_spec, new_cursors=table_spec, occur=table_spec)

    in_specs = (table_spec, table_spec, P("dp"), P("dp"), P("dp"), P("dp"),
                P())
    return jax.jit(_shard_map(local_step, mesh, in_specs, out_specs))


def _shard_map(fn, mesh: Mesh, in_specs, out_specs):
    """shard_map across jax versions: jax>=0.6 exposes it at top level
    with check_vma; earlier releases keep it in jax.experimental with
    the check_rep kwarg (same semantics)."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(fn, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=False)
    from jax.experimental.shard_map import shard_map
    return shard_map(fn, mesh=mesh, in_specs=in_specs,
                     out_specs=out_specs, check_rep=False)


# ---- device-to-device exchange stage (ISSUE 15) -------------------------

# weak refs: the registry must not pin compiled programs (and their
# captured meshes) past their owning server's life — it exists only so
# compile_stats can read live cache sizes
_EXCHANGE_STEPS: dict = {}      # seq -> weakref to jitted exchange fn
_EXCHANGE_SEQ = [0]


def _register_exchange_step(fn) -> None:
    import weakref
    seq = _EXCHANGE_SEQ[0]
    _EXCHANGE_SEQ[0] += 1
    try:
        _EXCHANGE_STEPS[seq] = weakref.ref(
            fn, lambda _r, s=seq: _EXCHANGE_STEPS.pop(s, None))
    except TypeError:           # not weakrefable on this jax: skip stats
        pass


def exchange_compile_stats() -> dict:
    """Jit-cache entry counts of LIVE exchange programs, folded into
    models.router_engine.compile_stats' recompile accounting."""
    out: dict = {}
    for seq, ref in sorted(_EXCHANGE_STEPS.items()):
        fn = ref()
        if fn is None:
            continue
        try:
            out[f"exchange_step_{seq}"] = fn._cache_size()
        except Exception:  # noqa: BLE001 — introspection is best-effort
            pass
    return out


def make_exchange_step(mesh: Mesh, *, seg_cap: int,
                       impl: "str | None" = None):
    """Build the jitted exchange program for `mesh` ('dp', 'route').

    Runs as a SECOND shard_map dispatch over the route step's result
    planes (mesh-colocated: launch cost is microseconds — the same
    posture as the CSR compaction's second call). Per (dp, route)
    device it

      1. flags its local messages clean/slow (capacity overflow, a
         shared-slot hit, or a matched fid on the slow mask) and
         psum-combines the verdict across 'route' — a message is clean
         only if EVERY shard saw it clean;
      2. attributes each fan-out row to its matched fid (the same
         flat-searchsorted trick as ops.compact), packs
         (msg, sid, gfid | opt << 24) records per OWNING delivery
         shard (sid % R — the PR 5 session-affinity discipline) into
         fixed-capacity segments [R, E, 3] with counted overflow;
      3. ring-rotates the segments R-1 rounds over 'route'
         (ops.pallas_exchange: remote-DMA kernel on TPU, ppermute twin
         elsewhere) so device (dp, d) ends up holding exactly the rows
         whose sessions it owns, from every source shard;
      4. merges the received segments source-major into ONE per-dest
         plan [E, 3] — (src asc, msg asc, row asc), the host gather
         path's exact per-session interleaving.

    Segment counts ride one tiny all_gather (control plane, 4 bytes per
    src×dst pair); the payload moves only on the ring. `seg_cap` (E) is
    a static capacity class — callers quantize it onto a ladder sized
    by an EWMA of observed per-dest row counts, and a window outgrowing
    its class reports ok&2 == 0 (the host gathers that window instead;
    correctness never depends on the class fitting).

    Call signature of the returned fn:
      exch(matches [B,R,M], rows [B,R,F], opts [B,R,F],
           shared_sids [B,R,K], overflow [B,R],
           aux: ExchangeAux ([R,Fc], [R,Fc], [R])) -> ExchangeResult
    """
    from emqx_tpu.ops.compact import _rows_searchsorted
    R = mesh.shape["route"]
    E = int(seg_cap)
    if impl is None:
        impl = exchange_rotate_impl()

    def local(matches, rows, opts, shared_sids, overflow,
              seg_len, fid_slow, fid_off):
        matches = matches[:, 0]            # [b, M] this shard's slice
        rows_l = rows[:, 0]                # [b, F]
        opts_l = opts[:, 0]
        shared_l = shared_sids[:, 0]       # [b, K]
        ovf_l = overflow[:, 0]             # [b]
        seg_len_l = seg_len[0]             # [Fc]
        fid_slow_l = fid_slow[0]
        fid_off_l = fid_off[0]             # scalar
        b, M = matches.shape
        F = rows_l.shape[1]
        my_r = jax.lax.axis_index("route")
        my_dp = jax.lax.axis_index("dp")

        # 1. clean verdict, combined across every route shard
        valid_m = matches >= 0
        mc = jnp.clip(matches, 0)
        slowfid = jnp.where(valid_m, fid_slow_l[mc], False).any(-1)
        bad_local = ovf_l | (shared_l >= 0).any(-1) | slowfid
        bad = jax.lax.psum(bad_local.astype(jnp.int32), "route") > 0

        # 2. row -> fid attribution + per-dest segment pack
        sl = jnp.where(valid_m, seg_len_l[mc], 0).astype(jnp.int32)
        ends = jnp.cumsum(sl, axis=-1)                        # [b, M]
        js = jnp.broadcast_to(jnp.arange(F, dtype=jnp.int32), (b, F))
        fidx = jnp.minimum(_rows_searchsorted(ends, js, F + 1), M - 1)
        gfid = jnp.take_along_axis(mc, fidx, axis=-1) + fid_off_l
        total = ends[:, -1:]
        valid_row = (js < total) & (rows_l >= 0)
        msg = my_dp * b + jnp.arange(b, dtype=jnp.int32)[:, None]
        word2 = gfid | ((opts_l.astype(jnp.int32) & 0x3F) << 24)
        dest = jnp.where(valid_row, rows_l % R, -1)

        n = b * F
        flat_dest = dest.reshape(n)
        flat_msg = jnp.broadcast_to(msg, (b, F)).reshape(n)
        flat_sid = rows_l.reshape(n)
        flat_w2 = word2.reshape(n)
        ks = jnp.arange(1, E + 1, dtype=jnp.int32)
        slot_valid = jnp.arange(E, dtype=jnp.int32)
        segs = []
        cnts = []
        pair_ovf = jnp.zeros((), bool)
        for d in range(R):                 # static, R is small
            m_d = flat_dest == d
            cnt = m_d.sum(dtype=jnp.int32)
            cum = jnp.cumsum(m_d.astype(jnp.int32))
            pos = jnp.minimum(
                jnp.searchsorted(cum, ks, side="left").astype(jnp.int32),
                n - 1)
            rec = jnp.stack([flat_msg[pos], flat_sid[pos],
                             flat_w2[pos]], axis=-1)          # [E, 3]
            k_ok = slot_valid < jnp.minimum(cnt, E)
            segs.append(jnp.where(k_ok[:, None], rec, -1))
            cnts.append(cnt)
            pair_ovf = pair_ovf | (cnt > E)
        seg = jnp.stack(segs)                                 # [R, E, 3]
        cnts = jnp.stack(cnts)                                # [R]

        # 3. ring rotation: after R-1 rounds, recv[s] holds the block
        # source shard s packed for dest my_r
        cnt_all = jax.lax.all_gather(cnts, "route")       # [R_src, R_dst]
        own = jax.lax.dynamic_index_in_dim(seg, my_r, 0, keepdims=False)
        recv = jax.lax.dynamic_update_index_in_dim(
            jnp.full((R, E, 3), -1, jnp.int32), own, my_r, 0)
        for k in range(1, R):
            send = jax.lax.dynamic_index_in_dim(
                seg, jax.lax.rem(my_r + k, R), 0, keepdims=False)
            got = ring_rotate(send, k, "route", R, impl=impl,
                              lead_axes=("dp",))
            recv = jax.lax.dynamic_update_index_in_dim(
                recv, got, jax.lax.rem(my_r - k + R, R), 0)

        # 4. source-major merge into the per-dest delivery plan
        src_cnt = jnp.minimum(jnp.take(cnt_all, my_r, axis=1), E)  # [R]
        ends_s = jnp.cumsum(src_cnt)
        starts = ends_s - src_cnt
        tot = ends_s[-1]
        c = jnp.arange(E, dtype=jnp.int32)
        src_of = jnp.minimum(
            jnp.searchsorted(ends_s, c, side="right").astype(jnp.int32),
            R - 1)
        plan = recv[src_of, jnp.clip(c - starts[src_of], 0, E - 1)]
        plan_ok = c < jnp.minimum(tot, E)
        plan = jnp.where(plan_ok[:, None], plan, -1)
        ok = (jnp.where(bad.any(), 0, 1)
              | jnp.where(pair_ovf | (tot > E), 0, 2)).astype(jnp.int32)
        return ExchangeResult(
            plan=plan[None, None],
            plan_cnt=jnp.minimum(tot, E)[None, None],
            src_cnt=src_cnt[None, None],
            ok=ok[None, None])

    per_dev = P("dp", "route")
    aux_spec = P("route")
    in_specs = (per_dev, per_dev, per_dev, per_dev, per_dev,
                aux_spec, aux_spec, aux_spec)
    out_specs = ExchangeResult(plan=per_dev, plan_cnt=per_dev,
                               src_cnt=per_dev, ok=per_dev)
    fn = jax.jit(_shard_map(local, mesh, in_specs, out_specs))
    _register_exchange_step(fn)
    return fn
