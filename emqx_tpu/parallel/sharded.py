"""shard_map'ed route step: filter-sharded trie × batch-sharded publishes.

Each 'route' shard owns a disjoint filter subset compiled into its own
RouterTables (same array shapes, different contents — stacked on a leading
axis). Publish batches shard over 'dp'. One step computes every (dp, route)
pair's local matches/fan-out; shared-subscription round-robin cursors stay
consistent across 'dp' shards by all-gathering per-slot occurrence counts
and rebasing each shard's cursor offset by the occurrences of lower dp ranks
(deterministic global batch order), then psum-advancing.

This is the ICI data plane replacing the reference's gen_rpc cross-node
forwarding (emqx_rpc.erl:20-60): instead of shipping messages to the node
that owns the route, every shard matches its slice and results ride the
interconnect (SURVEY.md §2.4 P6, §5.8).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

from emqx_tpu.models.router_engine import RouterTables, RouteResult
from emqx_tpu.ops.fanout import fanout_normal, shared_slots
from emqx_tpu.ops.match import match_batch
from emqx_tpu.ops.shapes import shape_match
from emqx_tpu.ops.shared import STRATEGY_ROUND_ROBIN, pick_members


def stack_tables(tables_list: list) -> RouterTables:
    """Stack per-shard RouterTables on a new leading axis (host, numpy).

    All shards must share array shapes — build each with the same
    node/slot/filter capacities (the host router buckets capacities pow2).
    """
    return jax.tree.map(lambda *xs: np.stack(xs), *tables_list)


def put_sharded(mesh: Mesh, tables_stacked: RouterTables, cursors_stacked,
                ledger=None):
    """Place stacked tables/cursors with their 'route' sharding.

    `ledger` (broker.hbm_ledger.HbmLedger, ISSUE 8): when given, the
    placed pytrees register as the mesh_tables / mesh_cursors
    categories so the shard tables stop being unaccounted HBM."""
    spec = NamedSharding(mesh, P("route"))
    # hbm: held right below under mesh_tables / mesh_cursors
    tables = jax.tree.map(lambda x: jax.device_put(x, spec), tables_stacked)
    cursors = jax.device_put(cursors_stacked, spec)  # hbm: held below
    if ledger is not None:
        tables = ledger.hold("mesh_tables", tables)
        cursors = ledger.hold("mesh_cursors", cursors)
    return tables, cursors


@functools.partial(jax.jit, donate_argnums=0)
def _apply_shard_update(full, new, idx):
    """Write one shard's tables into the stacked device arrays in place
    (donated buffers; the traced index keeps ONE compilation for all
    shards). Under a 'route' sharding XLA updates only the owning
    device's slice — nothing else moves."""
    return jax.tree.map(
        lambda f, n: jax.lax.dynamic_update_index_in_dim(f, n, idx, 0),
        full, new)


@jax.jit
def _apply_shard_update_keep(full, new, idx):
    """Non-donating variant: the PREVIOUS stacked tables stay valid —
    required when in-flight consumers (pipelined serving handles, a warm
    thread) still hold the old pytree. Costs a transient second copy of
    the updated arrays."""
    return jax.tree.map(
        lambda f, n: jax.lax.dynamic_update_index_in_dim(f, n, idx, 0),
        full, new)


def update_shard(tables_stacked, shard_idx: int, shard_tables,
                 donate: bool = True):
    """Incremental churn path (SURVEY §7 hard-part 1 under the mesh):
    subscription changes in ONE filter shard rebuild that shard host-side
    (same capacities as its siblings) and re-put ONLY its slice — the
    round-1 story (rebuild one shard -> restack -> re-upload everything)
    is gone.

    tables_stacked: device pytree with leading 'route' axis (donated
    unless donate=False — pass False whenever anything else may still
    read the old arrays).
    shard_tables: the ONE shard's host pytree (no leading axis).
    Returns the updated stacked pytree; the caller must adopt it (with
    donate=True the donated input is invalid afterwards).
    """
    n_shards = jax.tree.leaves(tables_stacked)[0].shape[0]
    if not 0 <= shard_idx < n_shards:
        # dynamic_update_index_in_dim would silently CLAMP and corrupt
        # the edge shard
        raise IndexError(f"shard_idx {shard_idx} out of range "
                         f"[0, {n_shards})")
    shapes_ok = jax.tree.map(
        lambda f, n: f.shape[1:] == n.shape, tables_stacked, shard_tables)
    if not all(jax.tree.leaves(shapes_ok)):
        raise ValueError(
            "shard tables shapes diverge from the stacked capacity "
            "classes; rebuild every shard with matching capacities")
    apply = _apply_shard_update if donate else _apply_shard_update_keep
    return apply(tables_stacked, shard_tables, jnp.int32(shard_idx))


def make_sharded_route_step(mesh: Mesh, *, backend: str = "trie",
                            frontier_cap: int = 16,
                            match_cap: int = 64, fanout_cap: int = 128,
                            slot_cap: int = 16):
    """Build the jitted multi-device route step for `mesh` ('dp','route').

    backend: 'trie' (RouterTables shards) or 'shapes' (ShapeRouterTables
    shards — the fast path).

    Call signature of the returned fn:
      step(tables [R,...], cursors [R,G], topics [B,L], lens [B],
           is_dollar [B], msg_hash [B], strategy scalar) -> RouteResult
    where per-topic outputs come back as [B, R, ...] (R = route shards,
    local filter ids per shard) and cursors as [R, G].
    """
    dp_size = mesh.shape["dp"]

    def local_step(tables, cursors, topics, lens, is_dollar, msg_hash,
                   strategy):
        tables = jax.tree.map(lambda x: x[0], tables)  # this shard's slice
        cursors = cursors[0]

        if backend == "shapes":
            mr = shape_match(tables.shapes, topics, lens, is_dollar)
        else:
            mr = match_batch(tables.trie, topics, lens, is_dollar,
                             frontier_cap=frontier_cap, match_cap=match_cap)
        fr = fanout_normal(tables.subs, mr.matches, fanout_cap=fanout_cap)
        sids, slot_oflow = shared_slots(tables.subs, mr.matches,
                                        slot_cap=slot_cap)

        # cross-dp deterministic round-robin: rebase cursors by the
        # occurrences seen in lower dp ranks, advance by the global total
        occur_local = jnp.zeros_like(cursors).at[
            jnp.clip(sids, 0).reshape(-1)].add(
            (sids >= 0).reshape(-1).astype(cursors.dtype))
        occur_all = jax.lax.all_gather(occur_local, "dp")        # [dp, G]
        my_dp = jax.lax.axis_index("dp")
        prefix = jnp.sum(jnp.where(
            jnp.arange(dp_size)[:, None] < my_dp, occur_all, 0), axis=0)
        is_rr = strategy == STRATEGY_ROUND_ROBIN
        sp = pick_members(tables.subs, cursors + jnp.where(is_rr, prefix, 0),
                          sids, strategy, msg_hash)
        total_occur = occur_all.sum(axis=0)
        new_cursors = jnp.where(is_rr, cursors + total_occur, cursors)

        overflow = mr.overflow | fr.overflow | slot_oflow
        res = RouteResult(
            matches=mr.matches, match_counts=mr.counts,
            rows=fr.rows, opts=fr.opts, fan_counts=fr.counts,
            shared_sids=sids, shared_rows=sp.rows, shared_opts=sp.opts,
            overflow=overflow, new_cursors=new_cursors, occur=total_occur)
        # per-topic outputs gain a 'route' axis at dim 1; cursor state keeps
        # its leading 'route' axis
        return RouteResult(
            matches=res.matches[:, None], match_counts=res.match_counts[:, None],
            rows=res.rows[:, None], opts=res.opts[:, None],
            fan_counts=res.fan_counts[:, None],
            shared_sids=res.shared_sids[:, None],
            shared_rows=res.shared_rows[:, None],
            shared_opts=res.shared_opts[:, None],
            overflow=res.overflow[:, None],
            new_cursors=res.new_cursors[None], occur=res.occur[None])

    table_spec = P("route")
    per_topic_spec = P("dp", "route")
    out_specs = RouteResult(
        matches=per_topic_spec, match_counts=per_topic_spec,
        rows=per_topic_spec, opts=per_topic_spec, fan_counts=per_topic_spec,
        shared_sids=per_topic_spec, shared_rows=per_topic_spec,
        shared_opts=per_topic_spec,
        overflow=per_topic_spec, new_cursors=table_spec, occur=table_spec)

    in_specs = (table_spec, table_spec, P("dp"), P("dp"), P("dp"), P("dp"),
                P())
    if hasattr(jax, "shard_map"):
        mapped = jax.shard_map(local_step, mesh=mesh, in_specs=in_specs,
                               out_specs=out_specs, check_vma=False)
    else:
        # jax < 0.6: the API lives in jax.experimental and the
        # replication-check kwarg is check_rep (same semantics)
        from jax.experimental.shard_map import shard_map
        mapped = shard_map(local_step, mesh=mesh, in_specs=in_specs,
                           out_specs=out_specs, check_rep=False)
    return jax.jit(mapped)
