"""Multichip serving: a live node routing through the dp×route mesh.

`ShardedRouteServer` is the multi-device sibling of
broker.device_engine.DeviceRouteEngine: it compiles the node's live
routing state into PER-SHARD RouterTables (filters partitioned by
crc32(filter) % route — the device-mesh analog of the reference's
`broker_pool` topic-hash serialization, emqx_broker.erl:427-428), serves
publish batches through parallel.sharded.make_sharded_route_step, and
consumes the [B, route, ...] RouteResult into real session deliveries.
It implements the PublishBatcher engine protocol, so a node boots with it
exactly like the single-chip engine and channels publish through the
same micro-batch window.

Churn model (simpler than the single-chip engine's dirty-filter +
delta-trie scheme): a subscription/route change dirties its filter's
SHARD; the next batch's `poll_rebuild` rebuilds the dirty shards
host-side with the snapshot's capacity classes and writes only their
slices into the stacked device arrays (parallel.sharded.update_shard —
one XLA dynamic_update_index_in_dim per shard, nothing else moves;
non-donating, so pipelined in-flight batches keep their pinned arrays).
Per-shard updates are synchronous-before-serve. A shard OUTGROWING its
capacity class kicks a BACKGROUND full rebuild (capture on the event
loop, compile+upload on a thread): while it runs, poll_rebuild returns
False and every batch routes host-side — correct, never stale, just
slower — until the swap; churn landing after the capture stays dirty
and follows as per-shard updates.

Cluster interplay: normal-route forwarding works exactly as the
single-chip consume (cluster.forward on the matched set). Shared groups
ride device slots in BOTH modes: standalone slots hold the local
members; under a cluster each shard's slots hold the CLUSTER-WIDE
membership (device_engine.capture_shared), remote members as
reserved-range sids (>= _REMOTE_SID_BASE) that consume turns into
directed `shared.deliver_fwd` RPCs — the reference's cross-node shared
dispatch (emqx_shared_sub.erl:239-268) with the pick already made on
the mesh. Membership replication dirties the filter's shard
(cluster.py:232 → note_member_change), so the synchronous per-shard
update keeps the slots cluster-fresh before every served batch.

Reference parity anchors: emqx_broker.erl:199-308 (the per-message path
this replaces), emqx_router.erl:77-86 (full replication this shards),
SURVEY.md §2.4 P2/P4/P6.
"""

from __future__ import annotations

import os
import threading
import time
import zlib
from typing import Optional

import numpy as np

from emqx_tpu.broker.device_engine import (_REMOTE_SID_BASE,
                                           DeviceRouteEngine, _is_rich,
                                           _next_pow2, _pack_opts,
                                           _unpack_opts, capture_shared)
from emqx_tpu.broker.deliver import DEFERRED, OPT_TABLE, LaneCounts
from emqx_tpu.broker.message import Message
from emqx_tpu.ops import intern as I
from emqx_tpu.ops.compact import csr_slices
from emqx_tpu.utils import topic as T

# gfid | packed_opt << 24 is the exchange wire word: global filter ids
# above this no longer fit next to the 6 subopt bits, so the stage
# stands down (counted) rather than corrupting rows
_EXCHANGE_MAX_GFID = 1 << 24


def resolve_device_exchange(configured=None) -> bool:
    """The one device-exchange resolution (ISSUE 15): config
    broker.device_exchange beats EMQX_TPU_EXCHANGE beats the built-in
    default-on. =0 restores the host gather/merge readback exactly —
    no exchange aux tables, no exchange program, no pipeline.exchange.*
    traffic — the A/B twin baseline the bit-identity tests pin."""
    if configured is not None:
        return bool(configured)
    return os.environ.get("EMQX_TPU_EXCHANGE", "1") \
        not in ("0", "false", "off")


class _ShardBuilt:
    """Host index of one shard's compiled tables."""

    __slots__ = ("fid_of", "fid_filter", "seg_len", "slot_key", "rich",
                 "host_extra", "remote_members", "seg_np", "fid_slow",
                 "cover_roots", "cover_covered")

    def __init__(self):
        # subscription covering (ISSUE 18): per-shard detection counters
        # for stats(); roots == len(fid_filter) when covering found
        # nothing (identity expansion)
        self.cover_roots = 0
        self.cover_covered = 0
        self.fid_of: dict[str, int] = {}
        self.fid_filter: list[str] = []
        self.seg_len: list[int] = []
        self.slot_key: list[tuple] = []      # local slot -> (filter, group)
        self.rich: set[str] = set()          # host-dict dispatch filters
        self.host_extra: list[tuple] = []    # too-deep: (filter, words)
        # device sid _REMOTE_SID_BASE+i -> (origin, remote_sid): consume
        # forwards picks for these over RPC (per shard, like _Built's)
        self.remote_members: list[tuple] = []
        # vectorized-consume companions (ISSUE 9 satellite; set once at
        # build, mirroring the single-chip _Built):
        self.seg_np = np.zeros(0, np.int64)   # seg_len as an array
        self.fid_slow = np.zeros(0, bool)     # rich OR snapshot slots


class _Handle:
    """One dispatched batch (PublishBatcher handle protocol).

    Pins the FULL snapshot it was prepared against — host index AND
    device tables/cursors — so a shard update applied while this batch
    is in the pipeline can neither re-index its decode nor swap the
    arrays under its dispatch (the batch serves the snapshot it saw,
    exactly like the single-chip engine's in-flight batches)."""

    __slots__ = ("subs", "built", "tables", "cursors", "enc", "res",
                 "np_res", "t0", "host_idx", "trace", "sub_traces",
                 "aux", "exch", "exch_bytes", "exch_fits")

    def __init__(self, subs, built, tables, cursors, enc, host_idx,
                 aux=None, exch_fits=True):
        self.subs = subs          # [[Message, ...]] — W=1: one sub-batch
        self.built = built        # list[_ShardBuilt] snapshot
        self.tables = tables      # stacked device pytree at prepare time
        self.cursors = cursors
        self.enc = enc
        self.host_idx = host_idx  # msg indexes forced host-side (too_long)
        self.res = None
        self.np_res = None
        self.trace = 0            # flight-recorder window trace (ISSUE 7)
        self.sub_traces = None    # per-sub trace ids (W=1 on the mesh)
        self.t0: Optional[float] = None
        self.aux = aux            # ExchangeAux snapshot (ISSUE 15)
        self.exch = None          # ExchangeResult once the stage ran
        self.exch_bytes = 0       # bytes the exchange landing cost
        self.exch_fits = exch_fits  # snapshot's gfid-space verdict


class ShardedRouteServer:
    """Serve a node's publishes through an n-device (dp×route) mesh."""

    def __init__(self, node, *, n_devices: Optional[int] = None,
                 dp: Optional[int] = None, mesh=None,
                 frontier_cap: int = 16, match_cap: int = 64,
                 fanout_cap: int = 128, slot_cap: int = 16,
                 level_cap: int = 16, max_batch: int = 256,
                 compact_readback: Optional[bool] = None,
                 delta_overlay: Optional[bool] = None,
                 supervisor=None, ledger=None,
                 dispatch_depth: Optional[int] = None,
                 device_exchange: Optional[bool] = None,
                 subscription_covering: Optional[bool] = None):
        from emqx_tpu.parallel.mesh import make_mesh
        self.node = node
        self.broker = node.broker
        self.router = node.broker.router
        if mesh is None:
            import jax
            n_devices = n_devices or len(jax.devices())
            mesh = make_mesh(n_devices, dp=dp)
        self.mesh = mesh
        self.n_route = mesh.shape["route"]
        self.n_dp = mesh.shape["dp"]
        self.frontier_cap = frontier_cap
        self.match_cap = match_cap
        self.fanout_cap = fanout_cap
        self.slot_cap = slot_cap
        self.level_cap = level_cap
        # pow2: _batch_class quantizes onto the doubling warm ladder — a
        # non-pow2 cap would name a class the ladder never compiles
        self.max_batch = _next_pow2(max_batch)
        self._STD_CLASSES = ((1, self.max_batch),)

        from emqx_tpu.parallel.sharded import make_sharded_route_step
        self.step = make_sharded_route_step(
            mesh, backend="trie", frontier_cap=frontier_cap,
            match_cap=match_cap, fanout_cap=fanout_cap, slot_cap=slot_cap)

        self.intern = I.InternTable()
        self.tables = None            # stacked device pytree [R, ...]
        self.cursors = None           # device [R, G_cap]
        self._builts: Optional[list[_ShardBuilt]] = None
        self._caps: Optional[dict] = None
        self.dirty_shards: set[int] = set()
        self._warm_classes: set[int] = set()
        self._warm_thread: Optional[threading.Thread] = None
        self._rebuild_thread: Optional[threading.Thread] = None
        self._capture_task = None     # pending chunked capture (loop ctx)
        # build generations: every capture start bumps _build_gen; a
        # build result adopts only if its gen is newer than the adopted
        # one, and a pending capture whose gen is no longer current is
        # SUPERSEDED (a sync rebuild() raced past it) — its result is
        # dropped rather than regressing the snapshot
        self._build_gen = 0
        self._adopted_gen = 0
        self._capture_gen = 0
        self._rebuild_backoff_until = 0.0
        self._lock = threading.Lock()   # dispatch thread vs loop rebuilds

        # CSR readback compaction (ISSUE 3), mesh edition: unlike the
        # single-chip engine the compaction is a SECOND small jitted
        # call in materialize (the mesh is co-located — launch cost is
        # microseconds, not a relay round trip), run over the stacked
        # [B, R, ...] planes reshaped to one [1, B*R] pseudo-window.
        # Payload classes are (Bp, P) keyed — independent of the
        # capacity classes, so they survive rebuilds — warmed by the
        # same background thread as the batch classes.
        if compact_readback is None:
            from emqx_tpu.broker.device_engine import _ENV_COMPACT
            compact_readback = _ENV_COMPACT
        self.compact_readback = bool(compact_readback)

        # delta overlay knob (ISSUE 4): accepted for config parity with
        # the single-chip engine, but the mesh's churn path is ALREADY
        # incremental — a subscription change dirties only its filter's
        # shard and poll_rebuild recompiles that shard host-side into
        # the stacked arrays (update_shard) before the next served
        # batch, i.e. a per-shard compaction with no world recapture.
        # The fused per-shard overlay (delta rows merged inside
        # make_sharded_route_step) is the designed next step; until
        # then stats() reports the mode so bench rows can't mistake the
        # per-shard rebuild for the single-chip overlay. The PR-2/3
        # handled-set sweep and per-slot staleness guard in _consume_one
        # are the churn-correctness invariants either path must keep.
        if delta_overlay is None:
            from emqx_tpu.broker.device_engine import _ENV_DELTA
            delta_overlay = _ENV_DELTA
        self.delta_overlay = bool(delta_overlay)
        # subscription covering (ISSUE 18), mesh edition: each shard's
        # trie holds only its local COVERING set and the per-shard
        # expansion CSR re-expands after the match stage — INSIDE
        # match_batch, so the exchange ships already-expanded rows and
        # the aggregation per filter-hash shard needs no new step. When
        # on, EVERY shard carries cover tables (empty ones where the
        # shard has no covered filters) so the stacked pytree stays
        # uniform; cover-set churn rides the existing per-shard
        # incremental rebuild (which re-detects covers for that shard).
        if subscription_covering is None:
            from emqx_tpu.broker.device_engine import _ENV_COVERING
            subscription_covering = _ENV_COVERING
        self.subscription_covering = bool(subscription_covering)
        # double-buffered window pipeline (ISSUE 9): the mesh gains the
        # same prepare/materialize split as the single-chip engine — at
        # dispatch_depth >= 2 the batcher runs up to that many windows'
        # stages concurrently (each pinning its own snapshot by
        # reference; the copy-on-write _builts discipline already
        # supports N in-flight handles), and dispatch() starts the
        # device→host readback transfers at return so materialize is
        # consume-on-arrival. The mesh step keeps NON-donating cursors:
        # its cursor adopt runs under _lock against per-shard updates —
        # the single-chip donation contract (sole ownership of the
        # in-buffer) does not hold here.
        from emqx_tpu.broker.batcher import resolve_dispatch_depth
        self.dispatch_depth = resolve_dispatch_depth(dispatch_depth)
        self._payload_mults = (8, 32, 128)
        self._pay_ewma: Optional[float] = None
        # device-to-device exchange stage (ISSUE 15): after the sharded
        # match, compact each shard's delivery rows to CSR segments
        # keyed by owning delivery shard (sid % route — the PR 5
        # session-affinity discipline) and ring-exchange them
        # device-to-device (ops.pallas_exchange: remote-DMA kernel on
        # TPU, ppermute twin elsewhere), so materialize lands ONLY the
        # per-dest final delivery plans instead of the gathered result
        # set. broker.device_exchange / EMQX_TPU_EXCHANGE =0 restores
        # host gather/merge exactly. Segment capacity classes (E) ride
        # an EWMA ladder like the CSR payload classes; a window whose
        # rows outgrow its class falls back to host gather (counted),
        # as does any window the clean-proof rejects (shared hit, rich
        # fid, overflow, cluster, too-deep host_extra).
        self.device_exchange = resolve_device_exchange(device_exchange)
        self.aux = None                   # device ExchangeAux [R, ...]
        self._exch_steps: dict = {}       # E -> jitted exchange program
        self._exch_warm: set[tuple] = set()      # {(Bp, E)}
        self._wanted_ecap: set[tuple] = set()
        self._exch_ewma: Optional[float] = None
        self._exch_fits = True            # global fid space < 2^24
        # combined fid->filter table across shards, memoized per
        # snapshot identity (the copy-on-write _builts list) — the
        # vectorized consume's plan hand-off indexes it
        self._flat_memo: Optional[tuple] = None
        self._compact_warm: set[tuple] = set()    # {(Bp, P)}
        self._wanted_pcap: set[tuple] = set()

        # fault-domain supervision (ISSUE 6): the mesh_exchange breaker
        # gates the whole sharded path (open → prepare_window returns
        # None → host route, the mesh's rung-2); the injection point
        # rides dispatch. A mesh fault also advances the batcher's
        # generic dispatch-stage breaker — both gates fall back to the
        # same host rung, so double accounting is harmless.
        self.sup = supervisor if supervisor is not None \
            else getattr(node, "supervisor", None)
        if self.sup is not None:
            self.sup.register_probe("mesh_exchange", self._probe_mesh)

        # HBM ledger (ISSUE 8): the stacked mesh shard tables + cursors
        # register under mesh_tables / mesh_cursors; dispatch handles
        # ride the pin sentinel like the single-chip engine's
        self.ledger = ledger if ledger is not None \
            else getattr(node, "hbm_ledger", None)

        # engine wiring (same hooks DeviceRouteEngine claims)
        self.broker.device_engine = self
        node.device_engine = self
        self.router.on_route_change = self.note_route_change

    # ---- churn tracking -------------------------------------------------
    def shard_of(self, topic_filter: str) -> int:
        return zlib.crc32(topic_filter.encode()) % self.n_route

    def note_route_change(self, topic_filter: str, added: bool) -> None:
        self.dirty_shards.add(self.shard_of(topic_filter))

    def note_member_change(self, real: str, group) -> None:
        self.dirty_shards.add(self.shard_of(real))

    # ---- build ----------------------------------------------------------
    def _bucket_filters(self) -> list[list[str]]:
        """One pass over the filter universe → per-shard lists (crc32
        once per filter, not once per filter per shard)."""
        buckets: list[list[str]] = [[] for _ in range(self.n_route)]
        for f in list(self.router.exact) + list(self.router.wildcards):
            buckets[self.shard_of(f)].append(f)
        return buckets

    def _capture_filters(self, fs, subs: dict, shared: dict) -> None:
        """Capture a sub-list of filters into subs/shared dicts — ONE
        body shared by the sync shard capture and the chunked async
        capture, so the two snapshots can never desynchronize. Shared
        groups capture cluster-wide membership with remote members as
        ((origin, sid), None) refs (device_engine.capture_shared — same
        scheme as the single-chip snapshot)."""
        broker = self.broker
        for f in fs:
            s = broker.subs.get(f)
            if s:
                subs[f] = list(s.items())
            cap = capture_shared(broker, f)
            if cap:
                shared[f] = cap

    def _capture_shard(self, mine: list[str]):
        """(filters, subs, shared) for one shard's bucketed filter list."""
        subs: dict = {}
        shared: dict = {}
        self._capture_filters(mine, subs, shared)
        return mine, subs, shared

    def _shard_dims(self, capture) -> dict:
        """Raw (un-padded) dims one shard's capture needs."""
        mine, subs, shared = capture
        n_slots = sum(len(g) for g in shared.values())
        return {
            "filters": len(mine),
            "nodes": sum(len(T.tokens(f)) for f in mine) + 1,
            "subs": sum(len(v) for v in subs.values()),
            "slots": n_slots,
            "members": sum(len(m[0]) for g in shared.values()
                           for m in g.values()),
        }

    @staticmethod
    def _caps_of(dims: dict) -> dict:
        return {k: _next_pow2(max(2, v)) for k, v in dims.items()}

    @staticmethod
    def _fits(dims: dict, caps: dict) -> bool:
        return all(dims[k] <= caps[k] for k in dims)

    def _build_shard(self, capture, caps: dict):
        """Compile one shard's capture into (built, RouterTables host,
        cursors row) with the given capacity classes."""
        from emqx_tpu.models.router_engine import RouterTables
        from emqx_tpu.ops.fanout import build_subtable
        from emqx_tpu.ops.trie import build_tables

        mine, subs_cap, shared_cap = capture
        b = _ShardBuilt()
        L = self.level_cap
        # filters deeper than the level cap can't ride the device trie:
        # they match host-side per message (rare; SURVEY §5.7's too-deep
        # fallback)
        deep = [f for f in mine if len(T.tokens(f)) > L]
        for f in deep:
            b.host_extra.append((f, T.tokens(f)))
        mine = [f for f in mine if len(T.tokens(f)) <= L]
        rows = np.full((len(mine), L), I.PAD, np.int32)
        lens = np.zeros(len(mine), np.int32)
        normal: dict[int, list] = {}
        filter_slots: dict[int, list] = {}
        shared_members: dict[int, list] = {}
        seg_len = [0] * len(mine)
        cursors = []
        for fid, f in enumerate(sorted(mine)):
            ws = T.tokens(f)
            ids = self.intern.encode_filter(ws)
            rows[fid, :len(ids)] = ids
            lens[fid] = len(ids)
            b.fid_of[f] = fid
            b.fid_filter.append(f)
            entries = []
            for sid, opts in subs_cap.get(f, ()):
                # rich subopts (v5 subids etc.) don't survive the packed
                # byte: keep the device rows for alignment but deliver
                # through the host dict (same split as the single-chip
                # engine's rich_filters)
                if _is_rich(opts):
                    b.rich.add(f)
                entries.append((sid, _pack_opts(opts)))
            if entries:
                normal[fid] = entries
                seg_len[fid] = len(entries)
            for gname in sorted(shared_cap.get(f, {})):
                members_raw, cursor = shared_cap[f][gname]
                slot = len(b.slot_key)
                b.slot_key.append((f, gname))
                members = []
                for sid, o in members_raw:
                    if isinstance(sid, tuple):
                        # remote member ref -> reserved-range device sid
                        dev_sid = _REMOTE_SID_BASE + len(b.remote_members)
                        b.remote_members.append(sid)
                        members.append((dev_sid, 0))
                    else:
                        members.append((sid, _pack_opts(o)))
                shared_members[slot] = members
                filter_slots.setdefault(fid, []).append(slot)
                cursors.append(cursor)
        b.seg_len = seg_len
        # vectorized-consume masks (ISSUE 9 satellite): a matched fid
        # flagged here sends its message down the ordering-safe slow
        # path — rich subopts (host-dict delivery) or snapshot shared
        # slots (pick/ack/cluster semantics). Groups created AFTER this
        # snapshot dirty their shard, and the fast path stands down
        # whenever dirty_shards is non-empty, so the live-state check
        # the per-message walk performed is preserved.
        nf = len(b.fid_filter)
        b.seg_np = np.asarray(seg_len, np.int64)
        b.fid_slow = np.zeros(max(1, nf), bool)
        for f in b.rich:
            b.fid_slow[b.fid_of[f]] = True
        for fid in filter_slots:
            b.fid_slow[fid] = True

        # subscription covering (ISSUE 18): detect cover relations among
        # this shard's filters, compile the trie over the COVERING set
        # only, and attach the expansion CSR so match_batch re-expands
        # matched covers into the exact full-set row BEFORE the exchange
        # ships it. The stacked mesh pytree must be structurally uniform
        # across shards and across incremental rebuilds, so the knob
        # alone decides attachment: when on, every shard carries cover
        # tables — an identity CSR (every filter its own root) where the
        # shard has no cover relations. Uniform constants (match_cap out
        # width, 256-candidate verify lane, caps["filters"] verify rows,
        # 1-row append region — mesh churn rides the per-shard rebuild,
        # not the append path) keep shard slices stack/update-compatible.
        cover_np = None
        roots = None
        if self.subscription_covering:
            from emqx_tpu.ops import cover as cover_mod
            if L <= cover_mod.MAX_KEY_LEVELS:
                n = len(mine)
                dollar = np.fromiter(
                    (f.startswith("$") for f in b.fid_filter), bool, n)
                if n >= 2:
                    covers, inc = cover_mod.detect_covers(
                        rows[:n], lens, dollar)
                    owner = cover_mod.assign_owners(covers, inc)
                else:
                    owner = np.full(n, -1, np.int64)
                keys = cover_mod.trie_order_keys(rows[:n], lens)
                cover_np = cover_mod.build_cover_tables(
                    rows[:n], lens, owner, keys,
                    fid_cap=caps["filters"], out_width=self.match_cap,
                    cand_cap=256, verify_cap=caps["filters"],
                    append_cap=1)
                roots = np.flatnonzero(owner < 0).astype(np.int64)
                b.cover_roots = int(roots.size)
                b.cover_covered = n - int(roots.size)

        if cover_np is not None:
            trie = build_tables(rows[roots], lens[roots],
                                filter_ids=roots,
                                node_capacity=caps["nodes"],
                                slot_capacity=4 * caps["nodes"])
            trie = trie._replace(cover=cover_np)
        else:
            trie = build_tables(rows[:len(mine)], lens,
                                node_capacity=caps["nodes"],
                                slot_capacity=4 * caps["nodes"])
        subs_tbl = build_subtable(
            caps["filters"], {k: v for k, v in normal.items()},
            filter_slots, shared_members,
            slot_cap=caps["slots"], sub_rows_cap=caps["subs"],
            fs_rows_cap=caps["slots"], member_rows_cap=caps["members"])
        cur = np.zeros(caps["slots"], np.int32)
        cur[:len(cursors)] = cursors
        return b, RouterTables(trie=trie, subs=subs_tbl), cur

    def _next_gen(self) -> int:
        self._build_gen += 1
        return self._build_gen

    def rebuild(self) -> None:
        """Full build, synchronously: capture every shard, compute shared
        capacity classes, compile, stack, place on the mesh. Direct
        callers (tests, boot warm-up) use this; the SERVING path never
        does — poll_rebuild hands full rebuilds to a background thread
        and serves host-side meanwhile. Bumps the build generation, so
        any in-flight background capture/build is superseded (its result
        would be staler than this one and is dropped at adopt)."""
        gen = self._next_gen()
        seen = set(self.dirty_shards)
        self.dirty_shards.clear()   # the capture below covers everything
        try:
            self._adopt_full_build(self._full_build(
                [self._capture_shard(mine)
                 for mine in self._bucket_filters()]), gen)
        except Exception:
            # a failed build must not eat the churn marks: the old
            # snapshot keeps serving and those shards still need repair
            # analysis: ok(cross-thread-state) — set |= set is ONE
            # C-level update under the GIL; idempotent re-mark (same
            # mark-restore discipline as the async capture path)
            self.dirty_shards |= seen
            raise

    def _full_build(self, captures):
        """Compile every shard from its capture (loop-free: thread-safe
        off the event loop)."""
        from emqx_tpu.parallel.sharded import put_sharded, stack_tables
        dims = [self._shard_dims(c) for c in captures]
        caps = self._caps_of({k: max(d[k] for d in dims)
                              for k in dims[0]})
        builts, tables, cursors = [], [], []
        for c in captures:
            b, t, cur = self._build_shard(c, caps)
            builts.append(b)
            tables.append(t)
            cursors.append(cur)
        stacked = stack_tables(tables)
        dev_tables, dev_cursors = put_sharded(
            self.mesh, stacked, np.stack(cursors), ledger=self.ledger)
        aux, fits = self._build_aux(builts, caps) \
            if self.device_exchange else (None, True)
        return caps, builts, dev_tables, dev_cursors, aux, fits

    # ---- exchange aux (ISSUE 15) ----------------------------------------
    def _aux_host_rows(self, b: _ShardBuilt, f_cap: int):
        """One shard's exchange companions, padded to the capacity
        class: per-fid fan-out segment lengths + the slow mask."""
        seg = np.zeros(f_cap, np.int32)
        slow = np.zeros(f_cap, bool)
        nf = len(b.fid_filter)
        seg[:nf] = b.seg_np
        slow[:nf] = b.fid_slow[:nf]
        return seg, slow

    @staticmethod
    def _fid_offsets(builts) -> "tuple[np.ndarray, bool]":
        """Global-fid base per shard — the device mirror of
        _flat_filters' offsets (both are the cumsum of per-shard filter
        counts in shard order, so device-packed gfids index the same
        flat table the host consume builds). Pure: returns (offsets,
        fits-in-packed-gfid-space); the caller adopts the verdict —
        writing live state from here would let a superseded background
        build override the adopted snapshot's verdict."""
        offs = np.zeros(len(builts), np.int32)
        total = 0
        for r, b in enumerate(builts):
            offs[r] = total
            total += len(b.fid_filter)
        return offs, total < _EXCHANGE_MAX_GFID

    def _build_aux(self, builts, caps):
        """Stack + place the exchange aux tables with the 'route'
        sharding next to the shard tables."""
        import jax
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as P

        from emqx_tpu.models.router_engine import ExchangeAux
        rows = [self._aux_host_rows(b, caps["filters"]) for b in builts]
        offs, fits = self._fid_offsets(builts)
        spec = NamedSharding(self.mesh, P("route"))
        # hbm: held by the adopter/caller under exchange_aux
        aux = ExchangeAux(
            seg_len=jax.device_put(np.stack([r[0] for r in rows]), spec),
            fid_slow=jax.device_put(np.stack([r[1] for r in rows]), spec),
            fid_off=jax.device_put(offs, spec))
        return aux, fits

    def _update_aux_shard(self, s: int, b: _ShardBuilt, builts):
        """Per-shard churn twin of _build_aux: slice-update the seg/slow
        planes (non-donating, like the tables) and re-place the tiny
        fid_off vector, which can shift for every shard after `s` when
        the shard's filter count changed."""
        import jax
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as P

        from emqx_tpu.models.router_engine import ExchangeAux
        from emqx_tpu.parallel.sharded import _apply_shard_update_keep
        seg, slow = self._aux_host_rows(b, self._caps["filters"])
        seg2, slow2 = _apply_shard_update_keep(
            (self.aux.seg_len, self.aux.fid_slow), (seg, slow),
            np.int32(s))
        offs, fits = self._fid_offsets(builts)
        # analysis: ok(cross-thread-state) — poll_rebuild calls this
        # inside `with self._lock:`; the live-snapshot verdict adopts
        # under the same lock _adopt_full_build takes (a background
        # build's verdict instead travels in its result tuple)
        self._exch_fits = fits
        # hbm: held by the caller under exchange_aux
        off_dev = jax.device_put(offs,
                                 NamedSharding(self.mesh, P("route")))
        return ExchangeAux(seg_len=seg2, fid_slow=slow2, fid_off=off_dev)

    def _hold(self, category: str, tree, owner=None):
        """Register a persistent device allocation with the HBM ledger
        (ISSUE 8); identity passthrough when the ledger is off."""
        if self.ledger is not None:
            return self.ledger.hold(category, tree, owner=owner)
        return tree

    def _adopt_full_build(self, result, gen: int) -> bool:
        caps, builts, dev_tables, dev_cursors, aux, fits = result
        with self._lock:
            if gen <= self._adopted_gen:
                return False    # a newer build already adopted: drop
            self._adopted_gen = gen
            self.tables = dev_tables
            self.cursors = dev_cursors
            self._builts = builts
            if caps != self._caps:
                # capacity classes are the jit signature: only a class
                # change invalidates compiled batch classes — clearing
                # on every rebuild kept the device permanently cold
                # under subscribe churn. The exchange programs trace
                # the aux planes' filter capacity, so their warm set
                # rides the same clock.
                self._warm_classes.clear()
                self._exch_warm.clear()
            self._caps = caps
            self.aux = self._hold("exchange_aux", aux) \
                if aux is not None else None
            self._exch_fits = fits
        return True

    def _kick_full_rebuild(self) -> None:
        """Background full rebuild: CAPTURE on the event-loop side in
        yielding chunks (a large routing state must not stall every
        connection for the whole capture — round-4 advisor finding;
        mirrors DeviceRouteEngine._capture_state_async), COMPILE +
        UPLOAD on a thread. Serving stays host-side until the swap
        (prepare_window returns None while this runs) — the single-chip
        engine's double-buffered rebuild, mesh edition.

        Dirty marks clear BEFORE the capture starts: churn landing
        mid-capture or mid-compile re-dirties its shard and follows as a
        per-shard update after the swap, which also self-heals any
        filter the chunked capture saw half-mutated. A failed build
        restores the marks and backs off before the next attempt — a
        persistent compile error must not become a tight respawn
        loop."""
        import asyncio
        if self._rebuild_thread is not None \
                and self._rebuild_thread.is_alive():
            return
        if self._capture_task is not None \
                and not self._capture_task.done():
            return
        if time.monotonic() < self._rebuild_backoff_until:
            return
        gen = self._next_gen()
        seen = set(self.dirty_shards)
        # analysis: ok(cross-thread-state) — set -= set is ONE C-level
        # difference_update under the GIL; removing exactly `seen`
        # keeps any mark the build thread adds concurrently (the
        # mark-restore discipline the gen checks below complete)
        self.dirty_shards -= seen
        try:
            loop = asyncio.get_running_loop()
        except RuntimeError:
            # no loop (tests / boot warm-up thread): sync capture is fine
            self._start_build_thread(
                [self._capture_shard(mine)
                 for mine in self._bucket_filters()], seen, gen)
            return
        self._capture_gen = gen
        from emqx_tpu.broker.supervise import guard_task
        self._capture_task = guard_task(
            loop.create_task(self._capture_then_build(seen, gen)),
            "mesh-capture", self.node.metrics)

    async def _capture_then_build(self, seen, gen: int) -> None:
        import asyncio
        chunk = 2048
        try:
            captures = []
            for mine in self._bucket_filters():
                subs: dict = {}
                shared: dict = {}
                for i in range(0, len(mine), chunk):
                    self._capture_filters(mine[i:i + chunk], subs, shared)
                    await asyncio.sleep(0)
                captures.append((mine, subs, shared))
        except Exception:   # noqa: BLE001 — surfaced + retried
            import logging
            logging.getLogger("emqx_tpu.serving").exception(
                "chunked mesh capture failed; backing off")
            # analysis: ok(cross-thread-state) — set |= set is ONE
            # C-level update under the GIL; re-marking is idempotent
            # against the build thread's concurrent |=
            self.dirty_shards |= seen
            self._rebuild_backoff_until = time.monotonic() + 5.0
            return
        if gen != self._build_gen:
            # superseded by a newer capture/rebuild: drop the captures,
            # but RESTORE the marks — if the superseding build failed,
            # these shards' churn would otherwise be permanently lost
            # analysis: ok(cross-thread-state) — set |= set is ONE
            # C-level update under the GIL; idempotent re-mark
            self.dirty_shards |= seen
            return
        self._start_build_thread(captures, seen, gen)

    def _start_build_thread(self, captures, seen, gen: int) -> None:
        def work():
            try:
                result = self._full_build(captures)
            except Exception:   # noqa: BLE001 — surfaced + retried
                import logging
                logging.getLogger("emqx_tpu.serving").exception(
                    "background mesh rebuild failed; backing off")
                self.node.metrics.inc("routing.mesh.rebuild_failed")
                # analysis: ok(cross-thread-state) — set |= set is ONE
                # C-level update under the GIL; the loop side's -= of
                # its own snapshot can't lose this re-mark
                self.dirty_shards |= seen
                self._rebuild_backoff_until = time.monotonic() + 5.0
                return
            if not self._adopt_full_build(result, gen):
                # a newer build won the race; its capture covered this
                # one's state, but conservatively re-mark the shards
                # analysis: ok(cross-thread-state) — set |= set is ONE
                # C-level update under the GIL; idempotent re-mark
                self.dirty_shards |= seen

        self._rebuild_thread = threading.Thread(target=work, daemon=True)
        self._rebuild_thread.start()

    def poll_rebuild(self) -> bool:
        """Apply pending churn BEFORE serving. Dirty shards rebuild
        host-side with the snapshot's capacities and only their device
        slices update (non-donating: in-flight handles still read the
        previous arrays); outgrowing a class kicks a BACKGROUND full
        rebuild. Returns False while the mesh cannot serve (no snapshot
        yet / full rebuild in progress) — callers route host-side."""
        if self._rebuild_thread is not None \
                and self._rebuild_thread.is_alive():
            return False
        if self._capture_task is not None \
                and not self._capture_task.done() \
                and self._capture_gen == self._build_gen:
            return False    # authoritative capture in progress
        if self._builts is None:
            self._kick_full_rebuild()
            return False
        if not self.dirty_shards:
            return True
        from emqx_tpu.parallel.sharded import update_shard
        buckets = self._bucket_filters()
        pending = sorted(self.dirty_shards)
        for s in pending:
            capture = self._capture_shard(buckets[s])
            if not self._fits(self._shard_dims(capture), self._caps):
                self._kick_full_rebuild()
                return False
            b, t, cur = self._build_shard(capture, self._caps)
            with self._lock:
                # update_shard emits all-new stacked arrays (donate=
                # False): re-register them so the ledger tracks the
                # live generation (the superseded arrays release on GC)
                self.tables = self._hold(
                    "mesh_tables", update_shard(self.tables, s, t,
                                                donate=False))
                cur_np = np.array(self.cursors)     # copy: jax buffers
                cur_np[s] = cur                     # are read-only
                import jax
                from jax.sharding import NamedSharding
                from jax.sharding import PartitionSpec as P
                self.cursors = self._hold("mesh_cursors", jax.device_put(
                    cur_np, NamedSharding(self.mesh, P("route"))))
                # copy-on-write: in-flight handles keep decoding with the
                # list they captured (their tables snapshot predates this
                # update), and the dispatch-side `_builts is h.built`
                # cursor guard must FIRE for them now
                builts = list(self._builts)
                builts[s] = b
                self._builts = builts
                if self.aux is not None:
                    # exchange aux rides the same per-shard update so
                    # a handle's (tables, aux) snapshot stays coherent
                    self.aux = self._hold(
                        "exchange_aux",
                        self._update_aux_shard(s, b, builts))
                self.dirty_shards.discard(s)
        return True

    # ---- PublishBatcher engine protocol ---------------------------------
    def _batch_class(self, n: int) -> int:
        return min(self.max_batch,
                   max(self.n_dp, _next_pow2(max(2, n))))

    def batch_class_warm(self, n_msgs: int) -> bool:
        return self._builts is not None and \
            self._batch_class(n_msgs) in self._warm_classes

    def _kick_class_warm(self) -> None:
        """Compile the standard batch classes off the serving path."""
        if self._warm_thread is not None and self._warm_thread.is_alive():
            return
        if self._builts is None:
            return

        def warm():
            # loop until every class is warm for the CURRENT capacity
            # signature: a caps-changing rebuild mid-loop clears earlier
            # classes, and a single ascending pass would never revisit
            # them (observed: only the last class warm, device cold)
            classes = []
            Bp = self.n_dp
            while Bp <= self.max_batch:
                classes.append(Bp)
                Bp *= 2
            for _ in range(8 * (len(classes) + 4)):   # bounded self-heal
                if self._builts is None:
                    return
                missing = [c for c in classes
                           if c not in self._warm_classes]
                # demand-registered compact readback classes re-run the
                # (cached) step for their Bp and compact ITS result, so
                # the compaction compiles against the step outputs'
                # actual shardings/dtypes (a numpy dummy would warm the
                # wrong program variant). list() first: materialize on
                # the executor thread .add()s concurrently, and a set
                # comprehension over the live set is a bytecode-level
                # iteration that would raise changed-size-during-iter
                # and kill the warm pass (list(set) is one atomic C call)
                want_c = sorted({bq for bq, P in list(self._wanted_pcap)
                                 if (bq, P) not in self._compact_warm})
                # demand-registered exchange classes (ISSUE 15) warm the
                # same way: re-run the step for their Bp and exchange
                # ITS result (right shardings); same atomic list()
                # snapshot discipline against concurrent .add()s
                want_e = sorted({bq for bq, E in list(self._wanted_ecap)
                                 if (bq, E) not in self._exch_warm})
                if not missing and not want_c and not want_e:
                    return
                self._warm_one((missing + want_c + want_e)[0])

        self._warm_thread = threading.Thread(target=warm, daemon=True)
        self._warm_thread.start()

    def _warm_one(self, Bp: int) -> None:
        import contextlib

        import jax
        from emqx_tpu.ops.shared import STRATEGY_ROUND_ROBIN
        tele = getattr(self.node, "pipeline_telemetry", None)
        enc = (np.full((Bp, self.level_cap), I.PAD, np.int32),
               np.zeros(Bp, np.int32), np.zeros(Bp, bool),
               np.zeros(Bp, np.int32))
        with self._lock:
            tables, cursors, caps = self.tables, self.cursors, self._caps
            aux = self.aux
        ctx = tele.compile_context(f"warm mesh B{Bp}") \
            if tele is not None else contextlib.nullcontext()
        with ctx:
            res = self.step(tables, cursors, *enc,
                            np.int32(STRATEGY_ROUND_ROBIN))
            jax.block_until_ready(res)
        with self._lock:
            if self._caps == caps:      # signature still current
                self._warm_classes.add(Bp)
        # wanted compact classes for this Bp compile against the step's
        # own outputs (right shardings); keyed (Bp, P) only — payload
        # classes are capacity-signature independent
        from emqx_tpu.ops.compact import compact_planes_jit
        # sorted() snapshots the set in one atomic C call — safe against
        # concurrent materialize-side .add()s
        for bq, P in sorted(self._wanted_pcap):
            if bq != Bp or (Bp, P) in self._compact_warm:
                continue
            cw = tele.compile_context(f"warm mesh B{Bp}c{P}") \
                if tele is not None else contextlib.nullcontext()
            with cw:
                cp = compact_planes_jit(
                    res.matches, res.rows, res.opts, res.fan_counts,
                    res.shared_sids, res.shared_rows, res.shared_opts,
                    payload_cap=P, match_holes=False)
                jax.block_until_ready(cp.offsets)
            self._compact_warm.add((Bp, P))
        # wanted exchange classes for this Bp (ISSUE 15): the exchange
        # program compiles against the warm step's own outputs plus the
        # live aux snapshot; keyed (Bp, E) and cleared with the caps
        # signature (the aux planes' filter capacity is traced)
        if aux is not None:
            from emqx_tpu.parallel.sharded import make_exchange_step
            for bq, E in sorted(self._wanted_ecap):
                if bq != Bp or (Bp, E) in self._exch_warm:
                    continue
                fn = self._exch_steps.get(E)
                if fn is None:
                    fn = make_exchange_step(self.mesh, seg_cap=E)
                    self._exch_steps[E] = fn
                ce = tele.compile_context(f"warm mesh B{Bp}x{E}") \
                    if tele is not None else contextlib.nullcontext()
                with ce:
                    ex = fn(res.matches, res.rows, res.opts,
                            res.shared_sids, res.overflow, *aux)
                    jax.block_until_ready(ex.plan)
                with self._lock:
                    if self._caps == caps:
                        self._exch_warm.add((Bp, E))

    def _probe_mesh(self) -> None:
        """mesh_exchange half-open probe (ISSUE 6): run the sharded
        step warm-shaped over an all-pad batch, off the serving path —
        the same call _warm_one already makes from background threads.
        With the exchange stage on, the probe also registers (and so
        runs) the exchange program at the probe's batch class: the
        domain covers the ring, and a breaker opened by a dead ring
        must not be re-closed by a probe that never touches it.
        Raising keeps the breaker open."""
        if self._builts is None:
            return      # nothing to probe: vacuous health
        if self.device_exchange and self.aux is not None \
                and self._exch_fits:
            key = (self.n_dp, self._choose_ecap(self.n_dp))
            self._wanted_ecap.add(key)
            # discard so _warm_one RE-RUNS the program even if the
            # class is warm — a dead ring behind a warm class would
            # otherwise pass the probe untraversed (the serving thread
            # at most gathers one window as cold_class meanwhile)
            self._exch_warm.discard(key)
        self._warm_one(self.n_dp)

    def max_fuse(self) -> int:
        return 1        # no window fusion on the mesh path (yet)

    def abandon(self, h: _Handle) -> None:
        h.res = None
        h.np_res = None
        h.exch = None
        if self.ledger is not None:
            self.ledger.unpin(id(h))

    def prepare(self, msgs: list[Message]) -> Optional[_Handle]:
        return self.prepare_window([msgs])

    def prepare_window(self, lives, gate_cold: bool = True) -> \
            Optional[_Handle]:
        """Stage 1 (event loop): encode one micro-batch (W=1).

        The single-chip engine's match cache / dedup layer is explicitly
        BYPASSED here: the mesh step matches against R per-shard table
        stacks whose slices are updated independently (update_shard), so
        there is no single snapshot id a cached row could be keyed to —
        a per-shard (shard, generation) key space is the prerequisite
        before the mesh can consult the same cache. Until then every
        mesh batch pays the full sharded match, and stats() reports the
        bypass so bench rows can't mistake it for a cold cache."""
        if self.sup is not None:
            self.sup.poll()     # supervision tick (probe launcher)
            if not self.sup.mesh_enabled():
                # mesh_exchange breaker open (ISSUE 6): the mesh's
                # rung-2 — every batch host-routes until the half-open
                # probe (a warm-shaped step off the serving path)
                # proves the mesh healthy again
                return None
        if not self.poll_rebuild() or self._builts is None or not lives:
            return None
        from emqx_tpu.ops.match import encode_topics_str
        msgs = lives[0]
        Bp = self._batch_class(len(msgs))
        if len(msgs) > Bp:
            return None
        enc, lens, dollar, too_long = encode_topics_str(
            self.intern, [m.topic for m in msgs], self.level_cap)
        host_idx = set(np.flatnonzero(too_long).tolist())
        pad = Bp - len(msgs)
        if pad:
            enc = np.vstack([enc, np.full((pad, self.level_cap), I.PAD,
                                          np.int32)])
            lens = np.concatenate([lens, np.zeros(pad, np.int32)])
            dollar = np.concatenate([dollar, np.zeros(pad, bool)])
        msg_hash = np.array(
            [zlib.crc32(m.topic.encode()) & 0x7FFFFFFF for m in msgs]
            + [0] * pad, np.int32)
        tele = getattr(self.node, "pipeline_telemetry", None)
        if tele is not None:
            tele.record_occupancy(f"b{Bp}", len(msgs) / Bp)
        with self._lock:
            h = _Handle(subs=[msgs], built=self._builts,
                        tables=self.tables, cursors=self.cursors,
                        enc=(enc, lens, dollar, msg_hash),
                        host_idx=host_idx, aux=self.aux,
                        exch_fits=self._exch_fits)
        if self.ledger is not None:
            # pin sentinel (ISSUE 8): mesh handles pin the whole
            # stacked snapshot by reference — a leaked one holds every
            # shard's HBM, so it rides the same stale-pin clock
            self.ledger.note_window()
            self.ledger.pin(id(h), h)
        return h

    def dispatch(self, h: _Handle) -> None:
        """Stage 2 (executor thread): run the mesh step on the handle's
        pinned snapshot; adopt cursors unless an update raced (then the
        freshly written cursor row wins — a one-batch fairness blip, not
        a correctness input). The batcher serializes dispatches on one
        thread, so cursor threading across batches is ordered."""
        import contextlib

        from emqx_tpu.ops.shared import STRATEGIES
        strategy = STRATEGIES.get(self.broker.shared_strategy, 0)
        tele = getattr(self.node, "pipeline_telemetry", None)
        t0 = time.perf_counter()
        with self._lock:
            # live cursors when no update raced (pipelined batches chain
            # round-robin state); the pinned ones otherwise — they are
            # the only set consistent with h.tables' slot layout
            cursors = self.cursors if self._builts is h.built \
                else h.cursors
        ctx = tele.compile_context(f"mesh B{h.enc[0].shape[0]}") \
            if tele is not None else contextlib.nullcontext()
        try:
            with ctx:
                if self.sup is not None:
                    # ISSUE 6 injection point (executor thread): the
                    # cross-shard exchange — exceptions propagate to
                    # the batcher's consumer (host replay) with the
                    # mesh domain noted here; hangs are caught by the
                    # consumer's watchdog deadline
                    self.sup.fire("mesh_exchange")
                h.res = self.step(h.tables, cursors, *h.enc,
                                  np.int32(strategy))
        except Exception as e:
            if self.sup is not None:
                self.sup.note_fault("mesh_exchange", e)
            raise
        with self._lock:
            if self._builts is h.built:    # no rebuild raced us
                self.cursors = self._hold("mesh_cursors",
                                          h.res.new_cursors)
        # the mesh_exchange domain covers the step AND the ring: the
        # domain's ok is recorded only once both succeeded — a note_ok
        # for the step alone would reset the breaker's consecutive-
        # fault count right before a persistently dead ring's
        # note_fault, and the breaker could never trip
        exchange_faulted = self._run_exchange(h)
        if self.sup is not None and not exchange_faulted:
            self.sup.note_ok("mesh_exchange")
        if self.dispatch_depth > 1:
            # ISSUE 9: start the readback transfers while this thread
            # still owns the dispatch slot — materialize(W) then hides
            # under dispatch(W+1)
            self._start_readback(h)
        if tele is not None:
            tele.observe_stage("dispatch", time.perf_counter() - t0)
        self._rec_span(h.trace, "dispatch", t0, track="dispatch")

    def _start_readback(self, h: _Handle) -> None:
        """Async-start the device→host transfer of the planes
        materialize will read (ISSUE 9): the small overflow/occur
        planes always; the dense result planes only when the CSR
        compaction will not supersede them (a compact materialize runs
        its own jitted pass first — prefetching the dense planes would
        waste exactly the bytes ISSUE 3 removed). The in-flight result
        registers with the HBM ledger under `pipeline_buffers`.
        Best-effort: a backend without async copies keeps the
        synchronous transfer in materialize."""
        r = h.res
        if r is None:
            return
        if self.ledger is not None:
            self._hold("pipeline_buffers", r)
        planes = [r.overflow, r.occur]
        if h.exch is not None:
            # exchange windows land only the occupied plan prefix —
            # prefetch the small control planes (ok probe, counts) ON
            # TOP of the base overflow/occur, which the gather rung
            # still needs if the clean-proof rejects this window; the
            # plan slice itself is cut after the counts arrive
            planes += [h.exch.ok, h.exch.plan_cnt, h.exch.src_cnt]
        else:
            Bp = int(r.matches.shape[0])
            P = self._choose_pcap(Bp)
            if P is None or (Bp, P) not in self._compact_warm:
                planes += [r.matches, r.rows, r.opts, r.shared_sids,
                           r.shared_rows, r.shared_opts]
        for a in planes:
            try:
                a.copy_to_host_async()
            except AttributeError:
                return
            except Exception:  # noqa: BLE001 — best-effort prefetch
                return

    def _choose_pcap(self, Bp: int) -> Optional[int]:
        """Payload class for a Bp-wide mesh readback, or None for dense.
        Same peak-biased-EWMA + pow2-multiple-ladder scheme as the
        single-chip engine (device_engine._choose_payload_cap); entry
        totals sum over shards, so the ladder multiplies Bp, not Bp*R."""
        if not self.compact_readback:
            return None
        dense = self.match_cap + 2 * self.fanout_cap + 3 * self.slot_cap
        mults = [m for m in self._payload_mults if m < dense]
        if not mults:
            return None
        ew = self._pay_ewma
        if ew is None:
            return mults[min(1, len(mults) - 1)] * Bp
        for m in mults:
            if m * Bp >= 2.0 * ew:
                return m * Bp
        return None

    def _note_payload(self, total: float) -> None:
        ew = self._pay_ewma
        self._pay_ewma = total if (ew is None or total > ew) \
            else 0.8 * ew + 0.2 * total

    # ---- exchange stage (ISSUE 15) --------------------------------------
    def _choose_ecap(self, Bp: int) -> int:
        """Per-dest exchange segment capacity class for a Bp-wide
        window: the smallest rung of a {pow2, 1.5*pow2} ladder holding
        1.25x the peak-biased EWMA of observed per-dest row counts —
        finer steps than the pow2-only payload ladder because every
        padded slot here is a byte the host lands. Bounded above by the
        everything-to-one-dest worst case: a dest's merged plan can
        hold every source shard's full fan-out plane for its dp
        block."""
        b_local = max(1, Bp // self.n_dp)
        cap_max = _next_pow2(b_local * self.fanout_cap
                             * max(1, self.n_route))
        ew = self._exch_ewma
        if ew is None:
            need = max(16, b_local // max(1, self.n_route))
        else:
            # class headroom over the peak-biased EWMA absorbs window-
            # to-window variance (an undersized class overflows whole
            # windows to gather); the padding it buys never crosses to
            # the host — materialize lands only the occupied prefix
            need = max(16, int(1.25 * ew) + 1)
        E = 16
        while E < need and E < cap_max:
            # 16, 24, 32, 48, 64, 96, 128, ...
            E = E * 3 // 2 if (E & (E - 1)) == 0 else E * 4 // 3
        return min(E, cap_max)

    def _note_exch(self, mx: float) -> None:
        ew = self._exch_ewma
        self._exch_ewma = mx if (ew is None or mx > ew) \
            else 0.8 * ew + 0.2 * mx

    def warm_exchange(self, n_msgs: int) -> bool:
        """Blocking warm of the exchange class serving `n_msgs`-wide
        batches (tests / bench warm-up — never the serving path, which
        demand-registers and warms in the background)."""
        if not self.device_exchange or self._builts is None \
                or self.aux is None:
            return False
        Bp = self._batch_class(n_msgs)
        key = (Bp, self._choose_ecap(Bp))
        self._wanted_ecap.add(key)
        self._warm_one(Bp)
        return key in self._exch_warm

    def _run_exchange(self, h: _Handle) -> bool:
        """Stage 2b (executor thread, right after the route step): run
        the device-to-device exchange program on the handle's pinned
        (result, aux) snapshot. Every stand-down is counted, never
        silent; a raising program degrades THIS window to host gather
        and advances the mesh_exchange breaker — a dead ring sheds to
        the gather rung instead of losing windows. Returns True iff
        the program FAULTED (the caller then withholds the domain's
        note_ok so the breaker's fault count actually accumulates);
        stand-downs are not faults."""
        if not self.device_exchange or h.aux is None or h.res is None:
            return False
        metrics = self.node.metrics
        if not h.exch_fits:
            # the handle's PINNED snapshot verdict, not the live one —
            # a rebuild adopted between prepare and dispatch must not
            # run this aux's gfids against the new verdict. Counted per
            # stood-down WINDOW (the every-stand-down-is-counted
            # invariant), not once per table build.
            metrics.inc("pipeline.exchange.fallback.gfid_space")
            return False
        if self.broker.cluster is not None \
                or self.broker.shared_strategy not in \
                self._dev_strategies() \
                or any(b.host_extra for b in h.built):
            metrics.inc("pipeline.exchange.fallback.precluded")
            return False
        if h.host_idx:
            # too-long topics route host-side per message: the device
            # plan can't represent them, so the window gathers
            metrics.inc("pipeline.exchange.fallback.host_idx")
            return False
        Bp = int(h.res.matches.shape[0])
        E = self._choose_ecap(Bp)
        if (Bp, E) not in self._exch_warm:
            # target class cold: background-warm it, and meanwhile keep
            # serving with the largest warm class that still holds the
            # observed peak (overflow falls back per window anyway) —
            # without this, every EWMA-driven resize would flap the
            # whole stage back to host gather until the compile landed
            self._wanted_ecap.add((Bp, E))
            self._kick_class_warm()
            ew = self._exch_ewma
            # sorted() snapshots the set in one atomic C call — safe
            # against the warm thread's concurrent .add()s
            cand = [e for bq, e in sorted(self._exch_warm)
                    if bq == Bp and (ew is None or e >= ew)]
            if not cand:
                metrics.inc("pipeline.exchange.cold_class")
                return False
            E = max(cand)
        fn = self._exch_steps.get(E)
        if fn is None:      # warm set says yes but builder raced: punt
            metrics.inc("pipeline.exchange.cold_class")
            return False
        t0 = time.perf_counter()
        r = h.res
        try:
            h.exch = fn(r.matches, r.rows, r.opts, r.shared_sids,
                        r.overflow, *h.aux)
        except Exception as e:  # noqa: BLE001 — degrade, don't lose
            if self.sup is not None:
                self.sup.note_fault("mesh_exchange", e)
            metrics.inc("pipeline.exchange.fallback.error")
            h.exch = None
            return True
        if self.ledger is not None:
            self._hold("exchange_buffers", h.exch)
        # bytes moved device-to-device: every device sends R-1 blocks
        # of [E, 3] int32 around the ring (counts ride one tiny
        # all_gather: R*4 bytes per device, included)
        R = self.n_route
        n_dev = self.n_dp * R
        metrics.inc("pipeline.exchange.rounds", R - 1)
        metrics.inc("pipeline.exchange.bytes_exchanged",
                    n_dev * ((R - 1) * E * 12 + R * 4))
        tele = getattr(self.node, "pipeline_telemetry", None)
        if tele is not None:
            tele.observe_stage("exchange", time.perf_counter() - t0)
        self._rec_span(h.trace, "exchange", t0, track="dispatch")
        return False

    def materialize(self, h: _Handle) -> None:
        """Stage 3 (executor thread): device → host readbacks.

        With compaction on (ISSUE 3) the [B, R, ...] result planes are
        compacted by a second small jitted call into one [1, B*R] CSR
        payload (lane = i*R + r) and only offsets + actual entries cross
        to the host; the small overflow/occur planes ride along either
        way. A window outgrowing its payload class reads the dense
        planes instead (row_overflow) — correctness never depends on the
        class fitting. Bytes transferred land in pipeline.readback.*."""
        tele = getattr(self.node, "pipeline_telemetry", None)
        metrics = self.node.metrics
        t0 = time.perf_counter()
        r = h.res
        if h.exch is not None and self._materialize_exchange(h, metrics):
            if tele is not None:
                tele.observe_stage("materialize",
                                   time.perf_counter() - t0)
            self._rec_span(h.trace, "materialize", t0,
                           track="materialize")
            return
        Bp = int(r.matches.shape[0])
        P = self._choose_pcap(Bp)
        if P is not None and (Bp, P) not in self._compact_warm:
            # cold compact class: dense this batch, background-warm it
            # (materialize runs off-loop, but an in-path XLA compile
            # would still stall this batch's pipeline slot for seconds)
            self._wanted_pcap.add((Bp, P))
            self._kick_class_warm()
            metrics.inc("routing.device.cold_compact_class")
            P = None
        csr_probe_bytes = 0
        if P is not None:
            from emqx_tpu.ops.compact import compact_planes_jit
            # match_holes=False: the mesh step is trie-backed (its NFA
            # emissions are densely packed, never hole-y like shapes)
            cp = compact_planes_jit(
                r.matches, r.rows, r.opts, r.fan_counts, r.shared_sids,
                r.shared_rows, r.shared_opts, payload_cap=P,
                match_holes=False)
            off = np.asarray(cp.offsets)[0]
            c3 = np.asarray(cp.counts3)[0]
            rovf = np.asarray(cp.row_overflow)
            self._note_payload(float(off[-1]))
            if rovf.any():
                metrics.inc("routing.device.compact_overflow")
                # the CSR probe planes already crossed: bill them to the
                # dense window below so the exported reduction stays
                # honest on overflowing workloads
                csr_probe_bytes = off.nbytes + c3.nbytes + rovf.nbytes
            else:
                pay = np.asarray(cp.payload)[0]
                overflow = np.asarray(r.overflow)
                occur = np.asarray(r.occur)
                h.np_res = {"csr": (off, c3, pay), "overflow": overflow,
                            "occur": occur}
                metrics.inc("pipeline.readback.bytes.compact",
                            off.nbytes + c3.nbytes + pay.nbytes
                            + overflow.nbytes + occur.nbytes)
                metrics.inc("pipeline.readback.windows.compact")
                if tele is not None:
                    tele.observe_stage("materialize",
                                       time.perf_counter() - t0)
                self._rec_span(h.trace, "materialize", t0,
                               track="materialize")
                return
        h.np_res = self._dense_np_res(r)
        metrics.inc("pipeline.readback.bytes.dense",
                    sum(a.nbytes for a in h.np_res.values())
                    + csr_probe_bytes)
        metrics.inc("pipeline.readback.windows.dense")
        if tele is not None:
            tele.observe_stage("materialize", time.perf_counter() - t0)
        self._rec_span(h.trace, "materialize", t0, track="materialize")

    @staticmethod
    def _dense_np_res(r) -> dict:
        return {
            "matches": np.asarray(r.matches),
            "rows": np.asarray(r.rows), "opts": np.asarray(r.opts),
            "shared_sids": np.asarray(r.shared_sids),
            "shared_rows": np.asarray(r.shared_rows),
            "shared_opts": np.asarray(r.shared_opts),
            "overflow": np.asarray(r.overflow),
            "occur": np.asarray(r.occur),      # [R, G]
        }

    def _fast_lane_live_ok(self, builts) -> bool:
        """THE post-dispatch live-state guard, shared by every fast
        lane (_consume_fast, the exchange materialize/consume): a
        cluster, churn marks, a raced snapshot swap, a rebuild in
        flight, a non-device strategy or too-deep filters mean the
        snapshot-proven clean masks can no longer be trusted. One
        predicate on purpose — a disqualifier added to one lane but
        not the other would silently diverge the fast paths from the
        per-message oracle. Note dirty_shards alone is NOT sufficient:
        a rebuild clears the marks at capture while the old snapshot
        keeps serving, and a per-shard sync update swaps the LIVE
        builts under an in-flight handle still pinned to the old list
        — either way the pinned fid_slow masks can miss a shared group
        subscribed after this handle's snapshot, and those messages
        must ride the per-message path, whose handled-set sweep checks
        live broker.shared."""
        broker = self.broker
        return not (broker.cluster is not None or self.dirty_shards
                    or builts is not self._builts
                    or (self._rebuild_thread is not None
                        and self._rebuild_thread.is_alive())
                    or (self._capture_task is not None
                        and not self._capture_task.done())
                    or broker.shared_strategy
                    not in self._dev_strategies()
                    or any(b.host_extra for b in builts))

    def _materialize_exchange(self, h: _Handle, metrics) -> bool:
        """Land the exchange result if every device reported clean +
        in-capacity; else count the reason and let the gather path land
        this window (the dense/CSR planes are outputs of the same step
        — transferring them is the fallback, computing them was free).
        Returns True when the exchange plans were landed."""
        if not self._fast_lane_live_ok(h.built):
            # disqualified already: land dense HERE, on the executor
            # thread, where the gather path always transfers — leaving
            # it for finish would block the event loop on a cold
            # multi-MB readback (the finish-time re-check below only
            # catches the rare churn that lands after this point)
            metrics.inc("pipeline.exchange.fallback.late")
            return False
        ex = h.exch
        ok = np.asarray(ex.ok)
        if not ok.size or int(ok.min()) != 3:
            if ok.size and not (ok & 2).all():
                # a segment/plan outgrew its capacity class: count it
                # and push the EWMA past the class so the next window
                # registers the bigger program
                metrics.inc("pipeline.exchange.overflow")
                cnt = np.asarray(ex.plan_cnt)
                if cnt.size:
                    # the true count is clamped at the class cap: bump
                    # one ladder rung past it and let the next landed
                    # windows' real maxima settle the EWMA
                    self._note_exch(float(cnt.max()) * 1.25)
            else:
                metrics.inc("pipeline.exchange.fallback.unclean")
            metrics.inc("pipeline.exchange.probe_bytes", ok.nbytes)
            return False
        cnt = np.asarray(ex.plan_cnt)
        scnt = np.asarray(ex.src_cnt)
        hi = int(cnt.max()) if cnt.size else 0
        self._note_exch(float(hi))
        # land only the occupied prefix of the plans: the class slack
        # (E - max cnt) never crosses the device→host link. Quantized
        # to 8 rows so the slice program set stays bounded (≤ E/8
        # cached variants per class).
        E = int(ex.plan.shape[2])
        hq = min(E, max(8, -(-hi // 8) * 8))
        plan = np.asarray(ex.plan[:, :, :hq])
        h.np_res = {"exchange": (plan, cnt, scnt)}
        # windows/host_landed_bytes are counted at CONSUME, once the
        # plans actually served — a finish-time disqualifier re-lands
        # dense, and billing this window on both paths would deflate
        # every bytes-per-window rate built on the counters
        h.exch_bytes = ok.nbytes + plan.nbytes + cnt.nbytes + scnt.nbytes
        return True

    def _land_dense(self, h: _Handle) -> dict:
        """Late gather fallback (finish-time disqualifier: churn or a
        cluster landed between dispatch and consume): transfer the
        dense planes from the still-held device result and bill them
        honestly as a dense readback window."""
        np_res = self._dense_np_res(h.res)
        self.node.metrics.inc("pipeline.exchange.fallback.late")
        self.node.metrics.inc("pipeline.readback.bytes.dense",
                              sum(a.nbytes for a in np_res.values()))
        self.node.metrics.inc("pipeline.readback.windows.dense")
        return np_res

    def _rec_span(self, trace_id: int, name: str, t0: float, *,
                  track: str) -> None:
        """Record one [t0, now] span on the node's flight recorder
        (no-op when tracing is off or the window carries no trace)."""
        rec = getattr(self.node, "flight_recorder", None)
        if rec is not None and trace_id:
            rec.record(trace_id, name, t0, time.perf_counter(),
                       track=track)

    def finish_sub(self, h: _Handle, k: int,
                   defer: bool = True) -> list[int]:
        """Stage 4 (event loop): consume into deliveries (W=1: k==0).

        Reuses the ISSUE-5 delivery-lane pool when the node carries one
        (`defer=True`, the pipelined path): messages whose every
        delivery is a plain local fan-out row are collected into the
        session-affine plan (_collect_clean), everything else —
        host-forced, overflow, shared groups, rich filters, too-deep
        host_extra, clustered — rides the plan's ordered barrier
        closures, so the per-session interleaving matches the inline
        loop exactly. `defer=False` (route_batch) stays inline."""
        tele = getattr(self.node, "pipeline_telemetry", None)
        t0 = time.perf_counter()
        msgs = h.subs[k]
        np_res = h.np_res
        plan = None
        pool = None
        if defer:
            pool = getattr(self.node, "deliver_lanes", None)
            if pool is not None and pool.active():
                plan = pool.new_plan(msgs)  # None without a loop
                if plan is not None:
                    plan.routed_device = True
                    # causal context → lanes; per-sub when the batcher
                    # attributed one (fused windows — max_fuse() is 1
                    # on the mesh today, so this is the W=1 lead trace)
                    plan.trace = h.sub_traces[k] \
                        if h.sub_traces and k < len(h.sub_traces) \
                        else h.trace
        # exchange windows (ISSUE 15): the landed per-dest plans ARE
        # the delivery work — consume them directly. A finish-time
        # disqualifier (churn/cluster landed after dispatch) re-lands
        # the dense planes from the still-held device result instead:
        # correctness first, the bytes billed honestly.
        fast = None
        if np_res is not None and "exchange" in np_res:
            fast = self._consume_exchange(msgs, np_res["exchange"],
                                          h.built, plan)
            if fast is None:
                # the landed-but-unconsumed plan bytes bill as probe
                # traffic; the window itself bills as the dense window
                # it becomes
                self.node.metrics.inc("pipeline.exchange.probe_bytes",
                                      h.exch_bytes)
                np_res = self._land_dense(h)
                h.np_res = np_res
            else:
                self.node.metrics.inc("pipeline.exchange.windows")
                self.node.metrics.inc(
                    "pipeline.exchange.host_landed_bytes", h.exch_bytes)
        if fast is None:
            # vectorized pre-pass (ISSUE 9 satellite): one numpy sweep
            # over the [B, route] planes serves every provably-clean
            # message; None (global disqualifier: cluster / dirty
            # shard / host_extra) keeps the pre-vectorized per-message
            # path below bit-exact
            fast = self._consume_fast(msgs, np_res, h.built, plan,
                                      h.host_idx)
        counts: list[int] = []
        for i, msg in enumerate(msgs):
            if fast is not None and fast[i] is not None:
                counts.append(0 if fast[i] is DEFERRED
                              else int(fast[i]))
                continue
            if i in h.host_idx or bool(np_res["overflow"][i].any()):
                if plan is not None:
                    counts.append(0)
                    plan.add_slow(i, lambda m=msg: self._host_route(m))
                else:
                    counts.append(self._host_route(msg))
                continue
            if plan is not None:
                rows = self._collect_clean(msg, i, np_res, h.built) \
                    if fast is None else None
                counts.append(0)
                if rows is not None:
                    plan.register_fast([i])
                    plan.add_rows_py(i, rows)
                else:
                    plan.add_slow(
                        i, lambda m=msg, j=i: self._consume_one(
                            m, j, np_res, h.built))
                continue
            counts.append(self._consume_one(msg, i, np_res, h.built))
        if "occur" in np_res:
            # exchange windows skip the occur plane: clean-proof means
            # no shared-slot occurrences, so there is nothing to mirror
            self._writeback_cursors(np_res["occur"], h.built)
        if plan is not None:
            out = LaneCounts(counts)
            out.plan = plan
            plan.target = out
            pool.submit(plan)
            counts = out
        if tele is not None:
            tele.observe_stage("deliver", time.perf_counter() - t0)
        self._rec_span(h.trace, "deliver", t0, track="consume")
        if self.ledger is not None:
            # consumed (lane plans keep the arrays alive by reference;
            # the pin tracks swap-blocking in-flight handles only)
            self.ledger.unpin(id(h))
        return counts

    def _flat_filters(self, builts):
        """(flat fid->filter list, per-shard offsets) across the
        snapshot's shards: global fid = offs[r] + local fid. Memoized on
        the copy-on-write _builts identity, so a shard update refreshes
        it and in-flight handles pinned to the old snapshot still
        resolve through their own builts list."""
        memo = self._flat_memo
        if memo is not None and memo[0] is builts:
            return memo[1], memo[2]
        flat: list[str] = []
        offs = np.zeros(self.n_route, np.int64)
        for r, b in enumerate(builts):
            offs[r] = len(flat)
            flat.extend(b.fid_filter)
        self._flat_memo = (builts, flat, offs)
        return flat, offs

    def _consume_fast(self, msgs, np_res, builts, plan, host_idx):
        """Vectorized mesh consume (ISSUE 9 satellite — the port of the
        single-chip commit-19f9192 design to the [B, route] planes):
        ONE numpy pass proves which messages are clean — no cluster, no
        dirty shard pending, no too-deep host_extra, no overflow, no
        shared-slot hit, no rich/slotted matched fid — then gathers
        every clean fan-out row grouped per shard. Python runs only at
        session hand-off (the _deliver calls, or zero per-row work at
        all when the delivery lanes take the rows). Returns a [B] list:
        per-message counts (DEFERRED under lanes), None entries for
        slow messages, or None WHOLE when a global disqualifier stands
        (callers then run the pre-vectorized per-message path
        unchanged). SHARDED_r05 measured the per-message Python walk at
        530 msg/s wall — this pass is what removes it."""
        broker = self.broker
        if not self._fast_lane_live_ok(builts):
            return None
        B = len(msgs)
        if B == 0:
            return []
        R = self.n_route
        slow = np.asarray(np_res["overflow"])[:B].reshape(B, -1) \
            .any(axis=1)
        if host_idx:
            slow[sorted(host_idx)] = True
        csr = np_res.get("csr")
        shard_rows = []
        if csr is not None:
            off, c3, pay = csr
            lanes = np.arange(B)[:, None] * R + np.arange(R)[None, :]
            slow |= (c3[:, 2][lanes] > 0).any(axis=1)
            for r in range(R):
                idx = np.arange(B) * R + r
                cm = c3[idx, 0].astype(np.int64)
                base = off[idx].astype(np.int64)
                total_m = int(cm.sum())
                mi = np.repeat(np.arange(B), cm)
                if total_m:
                    mcum = np.cumsum(cm) - cm
                    fids = pay[np.arange(total_m)
                               - np.repeat(mcum, cm)
                               + np.repeat(base, cm)].astype(np.int64)
                else:
                    fids = np.zeros(0, np.int64)
                cf = c3[idx, 1].astype(np.int64)
                fbase = base + cm
                obase = base + cm + cf

                def fetch(row_msg, col, fbase=fbase, obase=obase):
                    return (pay[fbase[row_msg] + col],
                            pay[obase[row_msg] + col])

                shard_rows.append((mi, fids, fetch))
        else:
            slow |= (np.asarray(np_res["shared_sids"])[:B] >= 0) \
                .any(axis=(1, 2))
            matches = np.asarray(np_res["matches"])
            for r in range(R):
                m = matches[:B, r]
                valid = m >= 0
                mi, _cols = np.nonzero(valid)
                fids = m[valid].astype(np.int64)
                rows_p = np_res["rows"]
                opts_p = np_res["opts"]

                def fetch(row_msg, col, r=r, rows_p=rows_p,
                          opts_p=opts_p):
                    return (rows_p[row_msg, r, col],
                            opts_p[row_msg, r, col])

                shard_rows.append((mi, fids, fetch))
        for r in range(R):
            mi, fids, _f = shard_rows[r]
            if fids.size:
                np.logical_or.at(slow, mi, builts[r].fid_slow[fids])
        out: list = [None] * B
        fast_ok = ~slow
        if not fast_ok.any():
            return out
        counts = np.zeros(B, np.int64)
        delivered = 0
        metrics = self.node.metrics
        deliver = broker._deliver
        if plan is not None:
            flat, offs = self._flat_filters(builts)
            plan.register_fast(np.flatnonzero(fast_ok))
        for r in range(R):
            b = builts[r]
            mi, fids, fetch = shard_rows[r]
            if not fids.size:
                continue
            keep = fast_ok[mi]
            mi_f, fids_f = mi[keep], fids[keep]
            if not mi_f.size:
                continue
            seg = b.seg_np[fids_f]
            total = int(seg.sum())
            if not total:
                continue
            row_msg, col, row_fid = DeviceRouteEngine._attribute_rows(
                mi_f, fids_f, seg, total)
            sid, opt = fetch(row_msg, col)
            valid = sid >= 0
            if plan is not None:
                # lane hand-off: one gather chunk per shard, global fid
                # space so every chunk shares ONE plan filter table
                plan.add_rows(row_msg[valid], sid[valid], opt[valid],
                              row_fid[valid] + offs[r], flat)
                continue
            fid_filter = b.fid_filter
            for bi, s, ob, fd in zip(row_msg[valid].tolist(),
                                     sid[valid].tolist(),
                                     opt[valid].tolist(),
                                     row_fid[valid].tolist()):
                if deliver(s, fid_filter[fd], msgs[bi],
                           dict(OPT_TABLE[ob & 0x3F])):
                    counts[bi] += 1
                    delivered += 1
        if plan is not None:
            for i in np.flatnonzero(fast_ok).tolist():
                out[i] = DEFERRED
            return out
        if delivered:
            metrics.inc("messages.routed.device", delivered)
        hooks = broker.hooks
        for i in np.flatnonzero(fast_ok).tolist():
            n = int(counts[i])
            if n == 0 and not msgs[i].is_sys:
                metrics.inc("messages.dropped")
                metrics.inc("messages.dropped.no_subscribers")
                hooks.run("message.dropped", (msgs[i],
                                              "no_subscribers"))
            out[i] = n
        return out

    def _consume_exchange(self, msgs, exch_pl, builts, plan):
        """Consume the exchanged per-dest delivery plans (ISSUE 15).

        Every message in an exchange-landed window is device-proven
        clean, so this is the _consume_fast fast lane fed from the
        plans instead of the gathered planes. Chunks hand to the
        delivery lanes per SOURCE shard in ascending order — and within
        a chunk, per dest, dp blocks ascending = global msg ascending —
        so a session's delivery sequence is bit-identical to the
        gather/merge walk: (src shard asc, msg asc, row asc).

        Returns the per-message counts list (DEFERRED under lanes), or
        None when a finish-time disqualifier stands (the rare churn
        that raced in AFTER materialize's own live-state check — the
        caller then pays one loop-side dense transfer, counted)."""
        broker = self.broker
        if not self._fast_lane_live_ok(builts):
            return None
        plan_p, _cnt_p, scnt = exch_pl
        B = len(msgs)
        if B == 0:
            return []
        R = self.n_route
        dpn = plan_p.shape[0]
        flat, _offs = self._flat_filters(builts)
        starts = np.cumsum(scnt, axis=2) - scnt       # [dp, dst, src]
        counts = np.zeros(B, np.int64)
        delivered = 0
        metrics = self.node.metrics
        deliver = broker._deliver
        if plan is not None:
            plan.register_fast(range(B))
        for r in range(R):
            pieces = []
            for d in range(R):
                for dp in range(dpn):
                    c = int(scnt[dp, d, r])
                    if c:
                        s0 = int(starts[dp, d, r])
                        pieces.append(plan_p[dp, d, s0:s0 + c])
            if not pieces:
                continue
            arr = np.concatenate(pieces) if len(pieces) > 1 \
                else pieces[0]
            msg_i = arr[:, 0]
            sid = arr[:, 1]
            w2 = arr[:, 2]
            gfid = w2 & (_EXCHANGE_MAX_GFID - 1)
            opt = (w2 >> 24) & 0x3F
            if plan is not None:
                plan.add_rows(msg_i, sid, opt, gfid, flat)
                continue
            for bi, s, ob, fd in zip(msg_i.tolist(), sid.tolist(),
                                     opt.tolist(), gfid.tolist()):
                if deliver(s, flat[fd], msgs[bi],
                           dict(OPT_TABLE[ob & 0x3F])):
                    counts[bi] += 1
                    delivered += 1
        if plan is not None:
            return [DEFERRED] * B
        if delivered:
            metrics.inc("messages.routed.device", delivered)
        hooks = broker.hooks
        out = []
        for i in range(B):
            n = int(counts[i])
            if n == 0 and not msgs[i].is_sys:
                metrics.inc("messages.dropped")
                metrics.inc("messages.dropped.no_subscribers")
                hooks.run("message.dropped", (msgs[i],
                                              "no_subscribers"))
            out.append(n)
        return out

    def _collect_clean(self, msg, i: int, np_res, builts):
        """Clean-proof + row collection for the delivery lanes: returns
        [(sid, packed_opt, filter)] when EVERY delivery of this message
        is a plain local fan-out row — standalone node, no shared group
        on any matched filter, no rich filter, no device shared-slot
        hit, no too-deep host_extra on any shard — else None (the
        ordering-safe _consume_one closure serves it)."""
        broker = self.broker
        if broker.cluster is not None:
            return None
        csr = np_res.get("csr")
        # pass 1 — fid-level disqualifier scan ONLY (no per-row work):
        # a slow message's deferred _consume_one repeats the full walk,
        # so collecting rows before the verdict would double the
        # per-row Python cost for exactly the messages that gain
        # nothing from it
        decoded = []
        for r in range(self.n_route):
            b = builts[r]
            if b.host_extra:
                return None
            if csr is not None:
                (row_m, rows, opts, srow, _prow, _orow) = csr_slices(
                    csr[0], csr[1], csr[2], i * self.n_route + r)
            else:
                row_m = np_res["matches"][i, r]
                rows = np_res["rows"][i, r]
                opts = np_res["opts"][i, r]
                srow = np_res["shared_sids"][i, r]
            for slot in srow:
                if slot >= 0:
                    return None
            for fid in row_m:
                if fid < 0:
                    continue
                f = b.fid_filter[fid]
                if f in b.rich or broker.shared.get(f):
                    return None
            decoded.append((b, row_m, rows, opts))
        # pass 2 — proven clean: collect the fan-out rows
        out: list[tuple] = []
        for b, row_m, rows, opts in decoded:
            off = 0
            for fid in row_m:
                if fid < 0:
                    continue
                f = b.fid_filter[fid]
                seg = b.seg_len[fid]
                for j in range(off, off + seg):
                    sid = int(rows[j])
                    if sid >= 0:
                        out.append((sid, int(opts[j]), f))
                off += seg
        return out

    def _writeback_cursors(self, occur, builts) -> None:
        """Mirror device round-robin advances onto the host
        SharedGroup.cursor — the next shard capture re-seeds the device
        row from it, so without this every churn event would reset the
        group's rotation (the single-chip engine's _sync_cursors)."""
        if self.broker.shared_strategy != "round_robin":
            return
        for r in range(self.n_route):
            b = builts[r]
            occ = occur[r]
            for slot in np.flatnonzero(occ[:len(b.slot_key)]):
                f, gname = b.slot_key[slot]
                g = self.broker.shared.get(f, {}).get(gname)
                if g is not None and g.members:
                    g.cursor = (g.cursor + int(occ[slot])) \
                        % len(g.members)

    def finish(self, h: _Handle) -> list[int]:
        # sync callers need final counts: inline consume, no lanes
        return self.finish_sub(h, 0, defer=False)

    # ---- consume --------------------------------------------------------
    def _host_route(self, msg: Message) -> int:
        broker = self.broker
        return broker._route(msg, broker.router.match(msg.topic))

    def _host_shared_dispatch(self, f: str, gname: str, msg) -> bool:
        """One group's host-side dispatch: cluster-wide pick under a
        cluster, local strategy pick standalone (single-chip engine's
        helper, mesh edition)."""
        broker = self.broker
        if broker.cluster is not None:
            return broker.cluster._dispatch_one_group(broker, f, gname,
                                                      msg)
        g = broker.shared.get(f, {}).get(gname)
        return bool(g and g.members
                    and broker._shared_pick_deliver(gname, f, g, msg))

    def _consume_one(self, msg, i: int, np_res, builts) -> int:
        broker = self.broker
        metrics = self.node.metrics
        cluster = broker.cluster
        dev_shared = self.broker.shared_strategy in self._dev_strategies()
        n = 0
        matched: list[str] = []
        handled: set[tuple] = set()   # (filter, group) the mesh served
        csr = np_res.get("csr")
        for r in range(self.n_route):
            b = builts[r]
            off = 0
            if csr is not None:
                # CSR lane (i, r) → i*R + r (ops.compact pseudo-window
                # layout): the valid entries of every plane in order,
                # no pad — the walks below are layout-agnostic
                (row_m, rows, opts, srow, prow, orow) = csr_slices(
                    csr[0], csr[1], csr[2], i * self.n_route + r)
            else:
                row_m = np_res["matches"][i, r]
                rows = np_res["rows"][i, r]
                opts = np_res["opts"][i, r]
                srow = np_res["shared_sids"][i, r]
                prow = np_res["shared_rows"][i, r]
                orow = np_res["shared_opts"][i, r]
            # fan-out rows are the concatenation of per-filter segments
            # in LOCAL fid order of the matched set
            for fid in row_m:
                if fid < 0:
                    continue
                f = b.fid_filter[fid]
                matched.append(f)
                seg = b.seg_len[fid]
                if f in b.rich:      # rich-subopts filter: host dict
                    n += broker.dispatch(f, msg)
                else:
                    for j in range(off, off + seg):
                        sid = int(rows[j])
                        if sid >= 0 and broker._deliver(
                                sid, f, msg, _unpack_opts(int(opts[j]))):
                            n += 1
                            metrics.inc("messages.routed.device")
                off += seg
            # too-deep filters: host match per message (rare); string
            # form so the $-topic exclusion rule applies
            for f, _fws in b.host_extra:
                if T.match(msg.topic, f):
                    matched.append(f)
                    n += broker.dispatch(f, msg)
            if dev_shared:
                for k, slot in enumerate(srow):
                    if slot < 0 or slot >= len(b.slot_key):
                        continue
                    f, gname = b.slot_key[slot]
                    handled.add((f, gname))
                    sid = int(prow[k])
                    if sid >= _REMOTE_SID_BASE:
                        # device picked a remote member: directed
                        # forward, the pick already made on the mesh
                        if cluster is not None:
                            origin, rsid = \
                                b.remote_members[sid - _REMOTE_SID_BASE]
                            cluster._spawn_fwd(
                                origin, "shared.deliver_fwd",
                                [f, gname, rsid, msg.to_wire()],
                                key=msg.topic)
                            n += 1
                            metrics.inc("messages.routed.device")
                            metrics.inc(
                                "messages.routed.device.remote_shared")
                        elif self._host_shared_dispatch(f, gname, msg):
                            n += 1   # cluster torn down since the build
                    elif sid >= 0:
                        # per-slot staleness guard (ADVICE r5): the pick
                        # was made against this handle's PINNED shard
                        # snapshot — if the member left the group
                        # mid-batch (session may still be alive, so
                        # _deliver would succeed wrongly) or the shard
                        # was re-dirtied since, re-pick host-side
                        # against live membership, mirroring the
                        # single-chip consume's dirty_slots check
                        grp = broker.shared.get(f, {}).get(gname)
                        stale = (grp is None or sid not in grp.members
                                 or self.shard_of(f) in self.dirty_shards)
                        if stale:
                            if self._host_shared_dispatch(f, gname, msg):
                                n += 1
                        elif broker._deliver(
                                sid, f, msg,
                                dict(_unpack_opts(int(orow[k])),
                                     share=gname)):
                            n += 1
                            metrics.inc("messages.routed.device")
                        elif broker.shared_dispatch_ack \
                                and self._host_shared_dispatch(
                                    f, gname, msg):
                            # nack with the ack protocol on: host
                            # re-pick (a nack from a live member with
                            # dispatch_ack off stays final, matching
                            # the host pick's semantics)
                            n += 1
        if not dev_shared:
            n += broker._dispatch_shared(msg, matched)
        else:
            # handled-set sweep (single-chip engine parity, round-5
            # advisor finding): any (filter, group) LIVE on a matched
            # filter but absent from this handle's pinned shard snapshot
            # dispatches host-side. That covers groups subscribed
            # between prepare and finish (the per-shard update landed
            # AFTER this batch's snapshot was pinned — they previously
            # got ZERO deliveries), and too-deep filters' groups, which
            # never get device slots (host_extra above, round-4 advisor
            # finding).
            for f in matched:
                names = set(broker.shared.get(f, ()))
                if cluster is not None:
                    names |= cluster._groups_by_real.get(f, set())
                for gname in names:
                    if (f, gname) in handled:
                        continue
                    handled.add((f, gname))
                    if self._host_shared_dispatch(f, gname, msg):
                        n += 1
        if cluster:
            n += cluster.forward(msg, matched)
        if n == 0 and not msg.is_sys:
            metrics.inc("messages.dropped")
            metrics.inc("messages.dropped.no_subscribers")
            broker.hooks.run("message.dropped", (msg, "no_subscribers"))
        return n

    @staticmethod
    def _dev_strategies():
        from emqx_tpu.ops.shared import STRATEGIES
        return STRATEGIES

    # ---- synchronous composition (publish_batch / tests / bench) --------
    def route_batch(self, msgs: list[Message],
                    wait: bool = False) -> Optional[list[int]]:
        """Route one batch synchronously. Returns None when the mesh
        cannot serve right now (first build / background rebuild in
        flight) — callers fall back to the host path. wait=True blocks
        until the mesh CAN serve (tests, dryrun, boot warm-up: never the
        event loop)."""
        if wait:
            t = self._rebuild_thread
            if t is not None and t.is_alive():
                t.join()
            if self._builts is None:
                self.rebuild()
            if not self.poll_rebuild():     # churn kicked a bg rebuild
                ct = self._capture_task
                if ct is not None and not ct.done():
                    # a loop-side chunked capture is pending and a
                    # wait=True caller (thread, can't pump the loop)
                    # needs a snapshot NOW: build synchronously — the
                    # generation bump supersedes the pending capture
                    self.rebuild()
                else:
                    t = self._rebuild_thread
                    if t is not None:
                        t.join()
                self.poll_rebuild()
        h = self.prepare(msgs)
        if h is None:
            return None
        h.t0 = time.perf_counter()
        self.dispatch(h)
        self.materialize(h)
        return self.finish(h)

    def stats(self) -> dict:
        return {
            "built": self._builts is not None,
            "mesh": {"dp": self.n_dp, "route": self.n_route},
            "filters": sum(len(b.fid_filter) for b in self._builts or ()),
            "shared_slots": sum(len(b.slot_key)
                                for b in self._builts or ()),
            "dirty_shards": sorted(self.dirty_shards),
            "caps": dict(self._caps or {}),
            "warm_classes": sorted(self._warm_classes),
            # the single-chip engine's snapshot-keyed match cache needs a
            # per-shard key space on the mesh — explicitly bypassed here
            # (see prepare_window), not merely cold
            "match_cache": "bypassed",
            "compact_readback": self.compact_readback,
            "dispatch_depth": self.dispatch_depth,
            # churn handling on the mesh: per-shard incremental rebuild
            # (see __init__) — not the single-chip fused overlay
            "delta_overlay": "per-shard-rebuild" if self.delta_overlay
            else False,
            "payload_ewma": round(self._pay_ewma, 1)
            if self._pay_ewma is not None else None,
            # device-to-device exchange stage (ISSUE 15): off restores
            # host gather/merge exactly; warm classes are (Bp, E)
            "device_exchange": bool(self.device_exchange
                                    and self._exch_fits),
            "exchange_warm": sorted(self._exch_warm),
            "exchange_ewma": round(self._exch_ewma, 1)
            if self._exch_ewma is not None else None,
            # subscription covering (ISSUE 18): per-shard detection,
            # aggregated; reduction = full set / covering set
            "subscription_covering": self.subscription_covering,
            "cover": {
                "roots": (nr := sum(b.cover_roots
                                    for b in self._builts or ())),
                "covered": (nc := sum(b.cover_covered
                                      for b in self._builts or ())),
                "reduction": round((nr + nc) / max(1, nr), 2),
            } if self.subscription_covering else None,
        }
