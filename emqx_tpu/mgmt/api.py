"""REST API route bindings.

Parity: emqx_mgmt_api_*.erl — status, nodes, brokers, stats, metrics,
clients (list/lookup/kick/subscriptions), subscriptions, routes, publish,
mqtt subscribe/unsubscribe, banned, alarms, rules (+rule test), listeners,
apps, cluster. Mounted under /api/v5 (the reference 5.0-dev surface).
"""

from __future__ import annotations

import base64
from typing import Optional

from emqx_tpu.mgmt.httpd import ApiError, HttpServer, Request, paginate
from emqx_tpu.mgmt.mgmt import Mgmt


def make_api(node, mgmt: Optional[Mgmt] = None, cluster=None,
             app_auth=None, host: str = "127.0.0.1",
             port: int = 0) -> HttpServer:
    mgmt = mgmt or Mgmt(node, cluster)
    auth_check = app_auth.is_authorized if app_auth is not None else None
    srv = HttpServer(host, port, auth_check=auth_check)
    P = "/api/v5"

    def route(method, path, handler):
        srv.route(method, P + path, handler)

    # ---- status (unauthenticated; emqx_mgmt_api_status) ----
    async def status(_req):
        return 200, {"status": "running", "node": node.name}
    srv.route("GET", "/status", status)
    route("GET", "/status", status)

    # ---- nodes / brokers ----
    async def nodes(_req):
        return await mgmt.list_nodes()
    route("GET", "/nodes", nodes)

    async def one_node(req):
        for n in await mgmt.list_nodes():
            if n["node"] == req.params["name"]:
                return n
        raise ApiError(404, "NOT_FOUND", "node not found")
    route("GET", "/nodes/:name", one_node)

    async def brokers(_req):
        return await mgmt.list_brokers()
    route("GET", "/brokers", brokers)

    # ---- stats / metrics ----
    async def stats(req):
        if req.query.get("aggregate") == "true":
            return await mgmt.stats(aggregate=True)
        return await mgmt.stats()
    route("GET", "/stats", stats)

    async def metrics(req):
        if req.query.get("aggregate") == "true":
            return await mgmt.metrics(aggregate=True)
        return await mgmt.metrics()
    route("GET", "/metrics", metrics)

    # ---- pipeline telemetry (device-path stage spans / occupancy /
    #      compile accounting — broker.telemetry snapshot schema) ----
    async def pipeline_stats(_req):
        tele = getattr(node, "pipeline_telemetry", None)
        if tele is None:
            raise ApiError(404, "SERVICE_UNAVAILABLE",
                           "pipeline telemetry not enabled")
        return tele.snapshot()
    route("GET", "/pipeline/stats", pipeline_stats)

    # ---- window-causal flight recorder (ISSUE 7): the post-mortem
    #      dump surface. Default: the overlap/bubble analysis + ring
    #      state; ?format=perfetto returns the Chrome trace-event JSON
    #      (load in https://ui.perfetto.dev or chrome://tracing) ----
    async def pipeline_trace(req):
        rec = getattr(node, "flight_recorder", None)
        if rec is None:
            raise ApiError(404, "SERVICE_UNAVAILABLE",
                           "flight recorder not enabled "
                           "(EMQX_TPU_TRACE=0?)")
        if req.query.get("format") == "perfetto":
            return rec.to_chrome()
        return {"summary": rec.analyze(),
                "ring": rec.state()}
    route("GET", "/pipeline/trace", pipeline_trace)

    # ---- device-resource observatory (ISSUE 8): the HBM ledger's
    #      `memory` section standalone — per-category live bytes /
    #      peak watermarks / pin ages + the backend memory_stats
    #      cross-check (the same document telemetry snapshot embeds) ----
    async def pipeline_memory(_req):
        ledger = getattr(node, "hbm_ledger", None)
        if ledger is None:
            raise ApiError(404, "SERVICE_UNAVAILABLE",
                           "HBM ledger not enabled "
                           "(EMQX_TPU_HBM_LEDGER=0?)")
        return ledger.section()
    route("GET", "/pipeline/memory", pipeline_memory)

    # ---- latency SLO observatory (ISSUE 13): the `latency` section
    #      standalone — per-(qos, path) ingress→routed / ingress→
    #      delivered percentiles, the SLO burn/verdict and the breach
    #      exemplars (each linked to its window's flight-recorder
    #      trace, triagable via /pipeline/trace) ----
    async def pipeline_latency(_req):
        obs = getattr(node, "latency_observatory", None)
        if obs is None:
            raise ApiError(404, "SERVICE_UNAVAILABLE",
                           "latency observatory not enabled "
                           "(EMQX_TPU_LATENCY=0?)")
        return obs.section()
    route("GET", "/pipeline/latency", pipeline_latency)

    # ---- adaptive overload governor (ISSUE 14): the `overload`
    #      section standalone — current grade, armed shed actions,
    #      last signal readings and the shed counters (the graded
    #      load-shed ladder's operator surface) ----
    async def pipeline_overload(_req):
        gov = getattr(node, "overload_governor", None)
        if gov is None:
            raise ApiError(404, "SERVICE_UNAVAILABLE",
                           "overload governor not enabled "
                           "(EMQX_TPU_OVERLOAD=0?)")
        tele = getattr(node, "pipeline_telemetry", None)
        if tele is not None:
            # the cheap standalone section — this endpoint gets polled
            # exactly while the broker is at capacity
            return tele.overload_section()
        return {"state": gov.state()}
    route("GET", "/pipeline/overload", pipeline_overload)

    # ---- clients ----
    async def clients(req):
        items = await mgmt.list_clients()
        if "username" in req.query:
            items = [c for c in items
                     if c.get("username") == req.query["username"]]
        return paginate(items, req)
    route("GET", "/clients", clients)

    async def client(req):
        c = await mgmt.lookup_client(req.params["clientid"])
        if c is None:
            raise ApiError(404, "CLIENTID_NOT_FOUND")
        return c
    route("GET", "/clients/:clientid", client)

    async def kick(req):
        if not await mgmt.kick_client(req.params["clientid"]):
            raise ApiError(404, "CLIENTID_NOT_FOUND")
        return 204, b""
    route("DELETE", "/clients/:clientid", kick)

    async def client_subs(req):
        return await mgmt.client_subscriptions(req.params["clientid"])
    route("GET", "/clients/:clientid/subscriptions", client_subs)

    # ---- subscriptions / routes ----
    async def subscriptions(req):
        items = await mgmt.list_subscriptions()
        if "clientid" in req.query:
            items = [s for s in items
                     if s.get("clientid") == req.query["clientid"]]
        return paginate(items, req)
    route("GET", "/subscriptions", subscriptions)

    async def routes(req):
        return paginate(mgmt.list_routes(), req)
    route("GET", "/routes", routes)
    route("GET", "/topics", routes)

    async def one_route(req):
        r = mgmt.lookup_route(req.params["topic"])
        if r is None:
            raise ApiError(404, "TOPIC_NOT_FOUND")
        return r
    route("GET", "/routes/:topic", one_route)
    route("GET", "/topics/:topic", one_route)

    # ---- publish / subscribe (emqx_mgmt_api_publish / _pubsub) ----
    def _decode_payload(body: dict) -> bytes:
        p = body.get("payload", "")
        if body.get("encoding") == "base64":
            return base64.b64decode(p)
        return p.encode() if isinstance(p, str) else bytes(p)

    async def publish(req):
        body = req.json() or {}
        if "topic" not in body:
            raise ApiError(400, "BAD_REQUEST", "topic required")
        n = await mgmt.publish(body["topic"], _decode_payload(body),
                               qos=int(body.get("qos", 0)),
                               retain=bool(body.get("retain", False)),
                               clientid=body.get("clientid", "http_api"),
                               properties=body.get("properties"))
        return {"deliveries": n}
    route("POST", "/publish", publish)
    route("POST", "/mqtt/publish", publish)

    async def publish_batch(req):
        out = []
        for body in req.json() or []:
            n = await mgmt.publish(
                body["topic"], _decode_payload(body),
                qos=int(body.get("qos", 0)),
                retain=bool(body.get("retain", False)),
                clientid=body.get("clientid", "http_api"),
                properties=body.get("properties"))
            out.append({"topic": body["topic"], "deliveries": n})
        return out
    route("POST", "/mqtt/publish_batch", publish_batch)

    async def mqtt_subscribe(req):
        body = req.json() or {}
        rc = await mgmt.subscribe_client(body.get("clientid", ""),
                                         body.get("topic", ""),
                                         int(body.get("qos", 0)))
        if rc is None:
            raise ApiError(404, "CLIENTID_NOT_FOUND")
        if rc > 2:
            raise ApiError(400, "SUBSCRIBE_FAILED",
                           f"reason code 0x{rc:02x}")
        return {"ok": True, "qos": rc}
    route("POST", "/mqtt/subscribe", mqtt_subscribe)

    async def mqtt_unsubscribe(req):
        body = req.json() or {}
        ok = mgmt.unsubscribe_client(body.get("clientid", ""),
                                     body.get("topic", ""))
        if not ok:
            raise ApiError(404, "CLIENTID_NOT_FOUND")
        return {"ok": True}
    route("POST", "/mqtt/unsubscribe", mqtt_unsubscribe)

    # ---- banned (emqx_mgmt_api_banned) ----
    async def banned_list(req):
        return paginate([{
            "as": b.kind, "who": b.value, "by": b.by, "reason": b.reason,
            "at": int(b.at), "until": int(b.until) if b.until else None}
            for b in node.banned.all()], req)
    route("GET", "/banned", banned_list)

    async def banned_create(req):
        body = req.json() or {}
        if body.get("as") not in ("clientid", "username", "peerhost"):
            raise ApiError(400, "BAD_REQUEST", "as must be clientid/"
                                               "username/peerhost")
        node.banned.create(body["as"], body["who"],
                           by=body.get("by", "mgmt_api"),
                           reason=body.get("reason", ""),
                           duration=body.get("seconds"))
        return 201, body
    route("POST", "/banned", banned_create)

    async def banned_delete(req):
        if not node.banned.delete(req.params["as"], req.params["who"]):
            raise ApiError(404, "NOT_FOUND")
        return 204, b""
    route("DELETE", "/banned/:as/:who", banned_delete)

    # ---- alarms ----
    async def alarms(req):
        which = req.query.get("activated")
        which = {"true": "activated", "false": "deactivated"}.get(
            which, "all")
        return node.alarms.get_alarms(which)
    route("GET", "/alarms", alarms)

    async def alarms_clear(_req):
        return {"cleared": node.alarms.delete_all_deactivated()}
    route("DELETE", "/alarms/deactivated", alarms_clear)

    # ---- rules (emqx_rule_engine_api) ----
    def _engine():
        eng = getattr(node, "rule_engine", None)
        if eng is None:
            raise ApiError(404, "SERVICE_UNAVAILABLE",
                           "rule engine not loaded")
        return eng

    async def rules_list(_req):
        return [r.to_map() for r in _engine().list_rules()]
    route("GET", "/rules", rules_list)

    async def rules_create(req):
        body = req.json() or {}
        try:
            rule = _engine().create_rule(
                body["sql"], body.get("actions", []),
                rule_id=body.get("id"),
                enabled=body.get("enabled", True),
                description=body.get("description", ""))
        except Exception as e:  # noqa: BLE001 — SQL errors are 400s
            raise ApiError(400, "BAD_SQL", str(e))
        return 201, rule.to_map()
    route("POST", "/rules", rules_create)

    async def rule_get(req):
        r = _engine().get_rule(req.params["id"])
        if r is None:
            raise ApiError(404, "RULE_NOT_FOUND")
        return r.to_map()
    route("GET", "/rules/:id", rule_get)

    async def rule_update(req):
        eng = _engine()
        r = eng.get_rule(req.params["id"])
        if r is None:
            raise ApiError(404, "RULE_NOT_FOUND")
        body = req.json() or {}
        if "sql" in body or "actions" in body or "description" in body:
            # validate EVERYTHING before touching the existing rule so a
            # bad update can never destroy a working rule
            from emqx_tpu.rules.sqlparser import parse_sql
            try:
                parse_sql(body.get("sql", r.sql))
            except Exception as e:  # noqa: BLE001
                raise ApiError(400, "BAD_SQL", str(e))
            actions = body.get("actions", r.actions)
            if not (isinstance(actions, list) and
                    all(isinstance(a, dict) and "name" in a
                        for a in actions)):
                raise ApiError(400, "BAD_REQUEST",
                               "actions must be a list of {name, params}")
            enabled = r.enabled
            eng.delete_rule(r.id)
            r = eng.create_rule(body.get("sql", r.sql), actions,
                                rule_id=req.params["id"], enabled=enabled,
                                description=body.get("description",
                                                     r.description))
        if "enabled" in body:   # applied last: validation already passed
            eng.enable_rule(r.id, bool(body["enabled"]))
        return r.to_map()
    route("PUT", "/rules/:id", rule_update)

    async def rule_delete(req):
        if not _engine().delete_rule(req.params["id"]):
            raise ApiError(404, "RULE_NOT_FOUND")
        return 204, b""
    route("DELETE", "/rules/:id", rule_delete)

    async def rule_test(req):
        body = req.json() or {}
        try:
            out = _engine().test_sql(body["sql"], body.get("context", {}))
        except Exception as e:  # noqa: BLE001
            raise ApiError(400, "BAD_SQL", str(e))
        return {"outputs": out}
    route("POST", "/rule_test", rule_test)

    # ---- listeners ----
    async def listeners(_req):
        return [{"node": node.name, "protocol": getattr(l, "protocol",
                                                        "mqtt:tcp"),
                 "bind": f"{getattr(l, 'bind', '0.0.0.0')}:"
                         f"{getattr(l, 'port', 0)}",
                 "current_conns": getattr(l, "current_conns", 0)}
                for l in node.listeners]
    route("GET", "/listeners", listeners)

    # ---- apps (api credentials; emqx_mgmt_api_apps) ----
    if app_auth is not None:
        async def apps_list(_req):
            return app_auth.list_apps()
        route("GET", "/apps", apps_list)

        async def apps_create(req):
            body = req.json() or {}
            try:
                secret = app_auth.add_app(body["app_id"],
                                          body.get("name", body["app_id"]),
                                          body.get("secret"),
                                          body.get("desc", ""))
            except ValueError:
                raise ApiError(409, "ALREADY_EXISTS")
            return 201, {"app_id": body["app_id"], "secret": secret}
        route("POST", "/apps", apps_create)

        async def apps_delete(req):
            if not app_auth.del_app(req.params["app_id"]):
                raise ApiError(404, "NOT_FOUND")
            return 204, b""
        route("DELETE", "/apps/:app_id", apps_delete)

    # ---- cluster ----
    async def cluster_info(_req):
        if cluster is None:
            return {"nodes": [node.name], "self": node.name}
        return cluster.info()
    route("GET", "/cluster", cluster_info)

    return srv
