"""Minimal asyncio HTTP/1.1 server for the management REST API.

Parity role: the minirest/cowboy HTTP listener (emqx_mgmt_http.erl). Routes
are (method, pattern) pairs where pattern segments starting with ':' bind
path params; handlers are sync or async callables
(request) -> (status, body_dict | bytes). JSON in/out; HTTP basic auth via a
pluggable checker (emqx_mgmt_auth analog).
"""

from __future__ import annotations

import asyncio
import base64
import json
import logging
import re
from typing import Any, Awaitable, Callable, Optional
from urllib.parse import parse_qsl, unquote, urlsplit

log = logging.getLogger("emqx_tpu.mgmt.httpd")

MAX_BODY = 8 << 20


class Request:
    def __init__(self, method: str, path: str, query: dict, headers: dict,
                 body: bytes, params: Optional[dict] = None):
        self.method = method
        self.path = path
        self.query = query
        self.headers = headers
        self.body = body
        self.params = params or {}

    def json(self) -> Any:
        if not self.body:
            return None
        return json.loads(self.body)

    def qint(self, name: str, default: int) -> int:
        try:
            return int(self.query.get(name, default))
        except ValueError:
            return default


Handler = Callable[[Request], Any]


class HttpServer:
    def __init__(self, host: str = "127.0.0.1", port: int = 0, *,
                 auth_check: Optional[Callable[[str, str], bool]] = None,
                 auth_exempt: tuple = ("/status", "/api/v5/status")):
        self.host, self.port = host, port
        self.auth_check = auth_check
        self.auth_exempt = auth_exempt
        self._routes: list[tuple[str, list[str], Handler]] = []
        self._server: Optional[asyncio.AbstractServer] = None

    def route(self, method: str, pattern: str, handler: Handler) -> None:
        self._routes.append((method.upper(),
                             [s for s in pattern.split("/") if s != ""],
                             handler))

    async def start(self) -> None:
        self._server = await asyncio.start_server(self._serve, self.host,
                                                  self.port)
        if self.port == 0:
            self.port = self._server.sockets[0].getsockname()[1]

    async def stop(self) -> None:
        if self._server:
            self._server.close()
            try:
                await asyncio.wait_for(self._server.wait_closed(), 2)
            except asyncio.TimeoutError:
                pass

    def _match(self, method: str, path: str):
        segs = [unquote(s) for s in path.split("/") if s != ""]
        for m, pat, handler in self._routes:
            if m != method or len(pat) != len(segs):
                continue
            params = {}
            ok = True
            for p, s in zip(pat, segs):
                if p.startswith(":"):
                    params[p[1:]] = s
                elif p != s:
                    ok = False
                    break
            if ok:
                return handler, params
        return None, None

    def _authorized(self, path: str, headers: dict) -> bool:
        # normalize like route matching does, so "/dashboard/" and "//"
        # hit the same exemption as "/dashboard" and "/"
        norm = "/" + "/".join(s for s in path.split("/") if s)
        if self.auth_check is None or path in self.auth_exempt \
                or norm in self.auth_exempt:
            return True
        hdr = headers.get("authorization", "")
        if hdr.lower().startswith("basic "):
            try:
                user, _, pwd = base64.b64decode(
                    hdr[6:].strip()).decode().partition(":")
            except Exception:  # noqa: BLE001
                return False
            return self.auth_check(user, pwd)
        if hdr.lower().startswith("bearer "):
            return self.auth_check("__bearer__", hdr[7:].strip())
        return False

    async def _serve(self, reader: asyncio.StreamReader,
                     writer: asyncio.StreamWriter) -> None:
        try:
            while True:
                line = await reader.readline()
                if not line or line in (b"\r\n", b"\n"):
                    return
                try:
                    method, target, _ver = line.decode().split()
                except ValueError:
                    return
                headers: dict[str, str] = {}
                while True:
                    h = await reader.readline()
                    if h in (b"\r\n", b"\n", b""):
                        break
                    k, _, v = h.decode().partition(":")
                    headers[k.strip().lower()] = v.strip()
                try:
                    clen = int(headers.get("content-length", 0))
                except ValueError:
                    writer.write(b"HTTP/1.1 400 Bad Request\r\n"
                                 b"content-length: 0\r\n"
                                 b"connection: close\r\n\r\n")
                    await writer.drain()
                    return
                if clen > MAX_BODY:
                    # refuse oversized bodies and close: reading part of the
                    # body would desync the stream (request smuggling)
                    writer.write(b"HTTP/1.1 413 Payload Too Large\r\n"
                                 b"content-length: 0\r\n"
                                 b"connection: close\r\n\r\n")
                    await writer.drain()
                    return
                body = await reader.readexactly(clen) if clen else b""
                url = urlsplit(target)
                query = dict(parse_qsl(url.query))
                status, payload = await self._dispatch(
                    method.upper(), url.path, query, headers, body)
                try:
                    if isinstance(payload, tuple) and len(payload) == 2 \
                            and isinstance(payload[0], (bytes, bytearray)) \
                            and isinstance(payload[1], str):
                        data, ctype = bytes(payload[0]), payload[1]
                    elif isinstance(payload, (bytes, bytearray)):
                        data, ctype = payload, "application/octet-stream"
                    else:
                        data = json.dumps(
                            payload, default=_json_default).encode()
                        ctype = "application/json"
                except (TypeError, ValueError):
                    # a handler returned something unserializable: the
                    # client must still get a response, not a dead socket
                    status = 500
                    data = b'{"code":"INTERNAL_ERROR"}'
                    ctype = "application/json"
                writer.write(
                    f"HTTP/1.1 {status} {_reason(status)}\r\n"
                    f"content-type: {ctype}\r\n"
                    f"content-length: {len(data)}\r\n"
                    "connection: keep-alive\r\n\r\n".encode() + data)
                await writer.drain()
                if headers.get("connection", "").lower() == "close":
                    return
        except (ConnectionError, asyncio.IncompleteReadError, OSError):
            pass
        finally:
            writer.close()

    async def _dispatch(self, method: str, path: str, query: dict,
                        headers: dict, body: bytes):
        if not self._authorized(path, headers):
            return 401, {"code": "UNAUTHORIZED",
                         "message": "bad credentials"}
        handler, params = self._match(method, path)
        if handler is None:
            return 404, {"code": "NOT_FOUND", "message": path}
        req = Request(method, path, query, headers, body, params)
        try:
            res = handler(req)
            if asyncio.iscoroutine(res) or isinstance(res, Awaitable):
                res = await res
        except json.JSONDecodeError:
            return 400, {"code": "BAD_REQUEST", "message": "invalid json"}
        except (KeyError, TypeError, ValueError) as e:
            # missing/mistyped body fields are client errors, not 500s
            return 400, {"code": "BAD_REQUEST",
                         "message": f"missing or invalid field: {e}"}
        except ApiError as e:
            return e.status, {"code": e.code, "message": e.message}
        except Exception as e:  # noqa: BLE001
            log.exception("handler error on %s %s", method, path)
            return 500, {"code": "INTERNAL_ERROR", "message": str(e)}
        if isinstance(res, tuple):
            return res
        return 200, res


class ApiError(Exception):
    def __init__(self, status: int, code: str, message: str = ""):
        self.status, self.code, self.message = status, code, message
        super().__init__(message)


def _json_default(o):
    if isinstance(o, bytes):
        try:
            return o.decode("utf-8")
        except UnicodeDecodeError:
            return base64.b64encode(o).decode()
    if isinstance(o, set):
        return sorted(o)
    return repr(o)


def _reason(status: int) -> str:
    return {200: "OK", 201: "Created", 204: "No Content",
            400: "Bad Request", 401: "Unauthorized", 404: "Not Found",
            409: "Conflict", 500: "Internal Server Error"}.get(status, "OK")


def paginate(items: list, req: Request) -> dict:
    """_page/_limit pagination envelope (emqx_mgmt_api:paginate)."""
    page = max(1, req.qint("_page", 1))
    limit = max(1, min(1000, req.qint("_limit", 100)))
    total = len(items)
    start = (page - 1) * limit
    return {"data": items[start:start + limit],
            "meta": {"page": page, "limit": limit, "count": total}}
