"""Management facade: node-local operations + cluster-wide fan-out.

Parity: emqx_mgmt.erl — lookup/list for nodes, brokers, clients,
subscriptions, routes; kick/clean ops; publish/subscribe on behalf of
clients. Cross-node calls ride the cluster rpc plane (the reference's
rpc:call fan-out in emqx_mgmt list_* functions); without a cluster every
call is local.
"""

from __future__ import annotations

import time
from typing import Any, Optional

from emqx_tpu.broker.message import make
from emqx_tpu.version import __version__

_BOOT_TS = time.time()


class Mgmt:
    def __init__(self, node, cluster=None):
        self.node = node
        self.cluster = cluster
        if cluster is not None:
            rpc = cluster.rpc
            rpc.register("mgmt.node_info", self._h_node_info)
            rpc.register("mgmt.broker_info", self._h_broker_info)
            rpc.register("mgmt.stats", self._h_stats)
            rpc.register("mgmt.metrics", self._h_metrics)
            rpc.register("mgmt.clients", self._h_clients)
            rpc.register("mgmt.client", self._h_client)
            rpc.register("mgmt.client_subs", self._h_client_subs)
            rpc.register("mgmt.subscriptions", self._h_subscriptions)

    # ---- helpers ----
    def _nodes(self) -> list[str]:
        if self.cluster is None:
            return [self.node.name]
        return self.cluster.membership.running_nodes()

    async def _fanout(self, fn: str, args: list) -> dict[str, Any]:
        if self.cluster is None:
            local = await getattr(self, "_h_" + fn.split(".", 1)[1])(*args)
            return {self.node.name: local}
        res = await self.cluster.rpc.multicall(self._nodes(), fn, args)
        return {n: v for n, v in res.items() if not isinstance(v, Exception)}

    # ---- node / broker info (emqx_mgmt:node_info, broker_info) ----
    async def _h_node_info(self) -> dict:
        import os
        try:
            la = os.getloadavg()
            load = {"load1": la[0], "load5": la[1], "load15": la[2]}
        except OSError:
            load = {}
        return {"node": self.node.name, "version": __version__,
                "node_status": "running",
                "uptime": int(time.time() - _BOOT_TS),
                "connections": self.node.cm.count(),
                "otp_release": "python", **load}

    async def _h_broker_info(self) -> dict:
        return {"node": self.node.name, "version": __version__,
                "sysdescr": "EMQX-TPU broker",
                "uptime": int(time.time() - _BOOT_TS),
                "datetime": time.strftime("%Y-%m-%d %H:%M:%S")}

    async def _h_stats(self) -> dict:
        return self.node.stats.sample()

    async def _h_metrics(self) -> dict:
        return self.node.metrics.all()

    async def _h_clients(self) -> list[dict]:
        out = []
        for cid, _chan in self.node.cm.all_channels():
            info = dict(self.node.cm.get_channel_info(cid) or {})
            info.update({"clientid": cid, "node": self.node.name,
                         "connected": True})
            out.append(info)
        for cid in getattr(self.node.cm, "_detached", {}):
            out.append({"clientid": cid, "node": self.node.name,
                        "connected": False})
        return out

    async def _h_client(self, clientid: str) -> Optional[dict]:
        for c in await self._h_clients():
            if c["clientid"] == clientid:
                return c
        return None

    async def _h_client_subs(self, clientid: str) -> list[dict]:
        broker = self.node.broker
        out = []
        for sid, cid in list(broker._sub_meta.items()):
            if cid != clientid:
                continue
            for f, opts in broker.subscriptions(sid):
                out.append({"clientid": clientid, "topic": f,
                            "qos": opts.get("qos", 0),
                            "node": self.node.name})
        return out

    async def _h_subscriptions(self) -> list[dict]:
        broker = self.node.broker
        out = []
        for f, members in broker.subs.items():
            for sid, opts in members.items():
                out.append({"clientid": broker._sub_meta.get(sid),
                            "topic": f, "qos": opts.get("qos", 0),
                            "node": self.node.name})
        for real, groups in broker.shared.items():
            for grp, g in groups.items():
                for sid, opts in g.members.items():
                    out.append({"clientid": broker._sub_meta.get(sid),
                                "topic": f"$share/{grp}/{real}",
                                "qos": opts.get("qos", 0),
                                "node": self.node.name})
        return out

    # ---- public API used by REST/CLI ----
    async def list_nodes(self) -> list[dict]:
        return list((await self._fanout("mgmt.node_info", [])).values())

    async def list_brokers(self) -> list[dict]:
        return list((await self._fanout("mgmt.broker_info", [])).values())

    async def _per_node_counters(self, fn: str, aggregate: bool) -> Any:
        per = await self._fanout(fn, [])
        if not aggregate:
            return [{"node": n, **v} for n, v in per.items()]
        agg: dict = {}
        for v in per.values():
            for k, x in v.items():
                if isinstance(x, (int, float)):
                    agg[k] = agg.get(k, 0) + x
        return agg

    async def stats(self, aggregate: bool = False) -> Any:
        return await self._per_node_counters("mgmt.stats", aggregate)

    async def metrics(self, aggregate: bool = False) -> Any:
        return await self._per_node_counters("mgmt.metrics", aggregate)

    async def list_clients(self) -> list[dict]:
        out: list[dict] = []
        for v in (await self._fanout("mgmt.clients", [])).values():
            out.extend(v)
        return out

    async def lookup_client(self, clientid: str) -> Optional[dict]:
        for v in (await self._fanout("mgmt.client", [clientid])).values():
            if v:
                return v
        return None

    async def client_subscriptions(self, clientid: str) -> list[dict]:
        out: list[dict] = []
        for v in (await self._fanout("mgmt.client_subs",
                                     [clientid])).values():
            out.extend(v)
        return out

    async def kick_client(self, clientid: str) -> bool:
        if self.cluster is not None:
            return await self.cluster.kick_session_global(clientid)
        return await self.node.cm.kick_session(clientid)

    async def list_subscriptions(self) -> list[dict]:
        out: list[dict] = []
        for v in (await self._fanout("mgmt.subscriptions", [])).values():
            out.extend(v)
        return out

    def list_routes(self) -> list[dict]:
        # the route table is fully replicated: local read is cluster truth
        if self.cluster is not None:
            tab = self.cluster.store.table("route")
            return [{"topic": t, "node": sorted(tab.origins(t))
                     or [self.node.name]}
                    for t in self.node.router.topics()]
        return [{"topic": t, "node": [self.node.name]}
                for t in self.node.router.topics()]

    def lookup_route(self, topic: str) -> Optional[dict]:
        for r in self.list_routes():
            if r["topic"] == topic:
                return r
        return None

    async def publish(self, topic: str, payload: bytes, qos: int = 0,
                      retain: bool = False, clientid: str = "http_api",
                      properties: Optional[dict] = None) -> int:
        from emqx_tpu.utils import topic as T
        try:
            # same topic-NAME validation the MQTT PUBLISH path enforces
            T.validate(topic, "name")
        except T.TopicError as e:
            raise ValueError(f"invalid topic name: {e}") from e
        msg = make(clientid, qos, topic, payload,
                   flags={"retain": retain},
                   headers={"properties": properties or {}})
        # awaited path so async extension hooks see API publishes too
        return await self.node.broker.publish_async(msg)

    async def subscribe_client(self, clientid: str, topic: str,
                               qos: int = 0) -> Optional[int]:
        """Install a subscription on a connected client's channel
        (emqx_mgmt:subscribe → the client's session). Returns the MQTT
        reason code (0..2 granted), or None if the client isn't here."""
        chan = self.node.cm.lookup_channel(clientid)
        if chan is None or not hasattr(chan, "mgmt_subscribe"):
            return None
        return await chan.mgmt_subscribe(topic, qos)

    def unsubscribe_client(self, clientid: str, topic: str) -> bool:
        chan = self.node.cm.lookup_channel(clientid)
        if chan is None or not hasattr(chan, "mgmt_unsubscribe"):
            return False
        return chan.mgmt_unsubscribe(topic)
