"""API application credentials (app id / secret).

Parity: emqx_mgmt_auth.erl — add_app/del_app/list_apps/is_authorized; the
REST listener authenticates HTTP basic credentials against this table
(`mgmt insert/lookup/update/delete/list` CLI, emqx_mgmt_cli.erl:64-106).
"""

from __future__ import annotations

import secrets
import time
from typing import Optional


class AppAuth:
    def __init__(self):
        self.apps: dict[str, dict] = {}

    def add_app(self, app_id: str, name: str,
                secret: Optional[str] = None, desc: str = "",
                status: bool = True,
                expired: Optional[int] = None) -> str:
        if app_id in self.apps:
            raise ValueError("already_existed")
        secret = secret or secrets.token_urlsafe(24)
        self.apps[app_id] = {"app_id": app_id, "name": name,
                             "secret": secret, "desc": desc,
                             "status": status, "expired": expired,
                             "created_at": int(time.time())}
        return secret

    def del_app(self, app_id: str) -> bool:
        return self.apps.pop(app_id, None) is not None

    def update_app(self, app_id: str, status: bool) -> bool:
        app = self.apps.get(app_id)
        if app is None:
            return False
        app["status"] = status
        return True

    def lookup_app(self, app_id: str) -> Optional[dict]:
        app = self.apps.get(app_id)
        if app is None:
            return None
        return {k: v for k, v in app.items() if k != "secret"}

    def list_apps(self) -> list[dict]:
        return [{k: v for k, v in a.items() if k != "secret"}
                for a in self.apps.values()]

    def is_authorized(self, app_id: str, secret: str) -> bool:
        app = self.apps.get(app_id)
        if app is None or not app["status"]:
            return False
        if app["expired"] is not None and time.time() > app["expired"]:
            return False
        return secrets.compare_digest(app["secret"], secret)
