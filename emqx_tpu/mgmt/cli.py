"""Management CLI: the emqx_ctl command surface.

Parity: emqx_ctl.erl (command registry) + emqx_mgmt_cli.erl:143-259 —
status, broker [stats|metrics], cluster join/leave/force-leave/status,
clients list/show/kick, routes list/show, subscriptions
list/show/add/del, plugins, vm, listeners, mgmt (API apps), banned, rules,
trace. Commands are async; output is returned as text (and printed by the
`emqx_ctl` entry point).
"""

from __future__ import annotations

from typing import Awaitable, Callable, Optional

from emqx_tpu.mgmt.mgmt import Mgmt

Command = Callable[..., Awaitable[str]]


class Cli:
    def __init__(self, node, mgmt: Optional[Mgmt] = None, cluster=None,
                 app_auth=None):
        self.node = node
        self.cluster = cluster
        self.mgmt = mgmt or Mgmt(node, cluster)
        self.app_auth = app_auth
        self._commands: dict[str, tuple[Command, str]] = {}
        self._register_builtins()

    # ---- registry (emqx_ctl:register_command) ----
    def register_command(self, name: str, fn: Command, usage: str) -> None:
        self._commands[name] = (fn, usage)

    def unregister_command(self, name: str) -> None:
        self._commands.pop(name, None)

    async def run(self, argv: list[str]) -> str:
        if not argv or argv[0] in ("help", "--help"):
            return self.usage()
        cmd = self._commands.get(argv[0])
        if cmd is None:
            return f"unknown command {argv[0]!r}\n" + self.usage()
        try:
            return await cmd[0](argv[1:])
        except (_Usage, ValueError):
            # bad numeric args etc. print the usage line, not a traceback
            return cmd[1]

    def usage(self) -> str:
        lines = ["Usage:"]
        for name in sorted(self._commands):
            lines.append(f"  {self._commands[name][1]}")
        return "\n".join(lines)

    def _register_builtins(self) -> None:
        r = self.register_command
        r("status", self._status, "status                 # broker status")
        r("broker", self._broker,
          "broker [stats|metrics] # broker info/stats/metrics")
        r("cluster", self._cluster,
          "cluster join <host:port> | leave | force-leave <node> | status")
        r("clients", self._clients,
          "clients list | show <clientid> | kick <clientid>")
        r("routes", self._routes, "routes list | show <topic>")
        r("topics", self._routes, "topics list | show <topic>")
        r("subscriptions", self._subs,
          "subscriptions list | show <clientid> | "
          "add <clientid> <topic> <qos> | del <clientid> <topic>")
        r("plugins", self._plugins, "plugins list")
        r("listeners", self._listeners, "listeners              # list")
        r("vm", self._vm, "vm                     # runtime load/memory")
        r("banned", self._banned,
          "banned list | add <as> <who> [<seconds>] | del <as> <who>")
        r("rules", self._rules, "rules list | show <id> | delete <id>")
        r("mgmt", self._mgmt,
          "mgmt list | insert <app_id> <name> | delete <app_id>")
        r("trace", self._trace,
          "trace start client|topic <value> <file> | "
          "trace stop client|topic <value> | trace list | "
          "trace device start <dir> | trace device stop")

    # ---- commands ----
    async def _trace(self, args) -> str:
        """emqx_ctl trace analog, plus the device-side jax.profiler trace
        (SURVEY §5.1): `trace device start <dir>` annotates every route
        dispatch as a profiler step so device execution decomposes from
        host/relay time in the captured trace."""
        if not args:
            raise _Usage()
        if args[0] == "device":
            eng = getattr(self.node, "device_engine", None)
            if eng is None:
                return "device routing is not enabled on this node"
            if args[1:2] == ["start"] and len(args) == 3:
                ok = eng.start_device_trace(args[2])
                return ("device trace started" if ok
                        else "backend has no profiler support")
            if args[1:2] == ["stop"]:
                eng.stop_device_trace()
                return "device trace stopped"
            raise _Usage()
        from emqx_tpu.apps.tracer import Tracer
        tr = self.node.get_app(Tracer)
        if tr is None:
            tr = self.node.register_app(Tracer(self.node).load())
        if args[0] == "list":
            rows = tr.lookup_traces()
            if not rows:
                return "no traces"
            return "\n".join(f"{r['type']:<9} {r['value']:<24} {r['path']}"
                             for r in rows)
        if args[0] == "start" and len(args) == 4 \
                and args[1] in ("client", "topic"):
            kind = "clientid" if args[1] == "client" else "topic"
            return ("trace started" if tr.start_trace(kind, args[2], args[3])
                    else "already tracing that")
        if args[0] == "stop" and len(args) == 3 \
                and args[1] in ("client", "topic"):
            kind = "clientid" if args[1] == "client" else "topic"
            return ("trace stopped" if tr.stop_trace(kind, args[2])
                    else "no such trace")
        raise _Usage()

    async def _status(self, _args) -> str:
        info = (await self.mgmt.list_brokers())[0]
        return (f"Node {self.node.name} is started\n"
                f"emqx_tpu {info['version']} is running")

    async def _broker(self, args) -> str:
        if not args:
            b = (await self.mgmt.list_brokers())[0]
            return "\n".join(f"{k:<12}: {v}" for k, v in b.items())
        if args[0] == "stats":
            s = await self.mgmt.stats(aggregate=True)
            return "\n".join(f"{k:<40}: {v}" for k, v in sorted(s.items()))
        if args[0] == "metrics":
            m = await self.mgmt.metrics(aggregate=True)
            return "\n".join(f"{k:<40}: {v}" for k, v in sorted(m.items()))
        raise _Usage()

    async def _cluster(self, args) -> str:
        if not args:
            raise _Usage()
        if self.cluster is None:
            return "node is not running in cluster mode"
        if args[0] == "status":
            info = self.cluster.info()
            return "\n".join(
                [f"Cluster status: {len(info['members'])} node(s)"] +
                [f"  {n}: {m['status']}"
                 for n, m in sorted(info["members"].items())])
        if args[0] == "join" and len(args) == 2:
            host, _, port = args[1].partition(":")
            await self.cluster.join(host, int(port or 5370))
            return f"Join the cluster successfully.\n" \
                   f"Cluster status: {self.cluster.info()['members']}"
        if args[0] == "leave" and len(args) == 1:
            await self.cluster.leave()
            return "Leave the cluster successfully."
        if args[0] == "force-leave" and len(args) == 2:
            await self.cluster.membership.force_leave(args[1])
            return f"Remove the node from cluster successfully: {args[1]}"
        raise _Usage()

    async def _clients(self, args) -> str:
        if args and args[0] == "list":
            rows = await self.mgmt.list_clients()
            return "\n".join(
                f"Client({c['clientid']}, username={c.get('username')}, "
                f"node={c.get('node')}, connected={c.get('connected')})"
                for c in rows) or "(none)"
        if len(args) == 2 and args[0] == "show":
            c = await self.mgmt.lookup_client(args[1])
            return f"Client({c})" if c else "Not Found."
        if len(args) == 2 and args[0] == "kick":
            ok = await self.mgmt.kick_client(args[1])
            return "ok" if ok else "Not Found."
        raise _Usage()

    async def _routes(self, args) -> str:
        if args and args[0] == "list":
            return "\n".join(f"{r['topic']} -> {','.join(r['node'])}"
                             for r in self.mgmt.list_routes()) or "(none)"
        if len(args) == 2 and args[0] == "show":
            r = self.mgmt.lookup_route(args[1])
            return f"{r['topic']} -> {','.join(r['node'])}" if r \
                else "Not Found."
        raise _Usage()

    async def _subs(self, args) -> str:
        if args and args[0] == "list":
            rows = await self.mgmt.list_subscriptions()
            return "\n".join(
                f"{s['clientid']} -> {s['topic']} (qos={s['qos']})"
                for s in rows) or "(none)"
        if len(args) == 2 and args[0] == "show":
            rows = await self.mgmt.client_subscriptions(args[1])
            return "\n".join(
                f"{s['clientid']} -> {s['topic']} (qos={s['qos']})"
                for s in rows) or "(none)"
        if len(args) == 4 and args[0] == "add":
            rc = await self.mgmt.subscribe_client(args[1], args[2],
                                                  int(args[3]))
            if rc is None:
                return "Error: client not found"
            return "ok" if rc <= 2 else f"Error: reason code 0x{rc:02x}"
        if len(args) == 3 and args[0] == "del":
            ok = self.mgmt.unsubscribe_client(args[1], args[2])
            return "ok" if ok else "Error: client not found"
        raise _Usage()

    async def _plugins(self, _args) -> str:
        plugins = getattr(self.node, "plugins", None)
        if plugins is None:
            return "(none)"
        return "\n".join(
            f"Plugin({p['name']}, enabled={p['enabled']})"
            for p in plugins.list()) or "(none)"

    async def _listeners(self, _args) -> str:
        out = []
        for l in self.node.listeners:
            out.append(f"{getattr(l, 'protocol', 'mqtt:tcp')} on "
                       f"{getattr(l, 'bind', '0.0.0.0')}:"
                       f"{getattr(l, 'port', 0)}\n"
                       f"  current_conn: {getattr(l, 'current_conns', 0)}")
        return "\n".join(out) or "(none)"

    async def _vm(self, _args) -> str:
        import os
        import resource
        usage = resource.getrusage(resource.RUSAGE_SELF)
        try:
            la = os.getloadavg()
        except OSError:
            la = (0, 0, 0)
        return (f"cpu/load1: {la[0]:.2f}\ncpu/load5: {la[1]:.2f}\n"
                f"cpu/load15: {la[2]:.2f}\n"
                f"memory/rss_kb: {usage.ru_maxrss}")

    async def _banned(self, args) -> str:
        if args and args[0] == "list":
            return "\n".join(
                f"banned {b.kind} {b.value} by {b.by} until "
                f"{b.until or 'forever'}"
                for b in self.node.banned.all()) or "(none)"
        if len(args) >= 3 and args[0] == "add":
            dur = float(args[3]) if len(args) > 3 else None
            self.node.banned.create(args[1], args[2], by="cli",
                                    duration=dur)
            return "ok"
        if len(args) == 3 and args[0] == "del":
            return "ok" if self.node.banned.delete(args[1], args[2]) \
                else "Not Found."
        raise _Usage()

    async def _rules(self, args) -> str:
        eng = getattr(self.node, "rule_engine", None)
        if eng is None:
            return "rule engine not loaded"
        if args and args[0] == "list":
            return "\n".join(
                f"Rule({r.id}, enabled={r.enabled}): {r.sql}"
                for r in eng.list_rules()) or "(none)"
        if len(args) == 2 and args[0] == "show":
            r = eng.get_rule(args[1])
            return str(r.to_map()) if r else "Not Found."
        if len(args) == 2 and args[0] == "delete":
            return "ok" if eng.delete_rule(args[1]) else "Not Found."
        raise _Usage()

    async def _mgmt(self, args) -> str:
        if self.app_auth is None:
            return "mgmt auth not configured"
        if args and args[0] == "list":
            return "\n".join(f"app_id: {a['app_id']}, name: {a['name']}, "
                             f"status: {a['status']}"
                             for a in self.app_auth.list_apps()) or "(none)"
        if len(args) == 3 and args[0] == "insert":
            secret = self.app_auth.add_app(args[1], args[2])
            return f"AppSecret: {secret}"
        if len(args) == 2 and args[0] == "delete":
            return "ok" if self.app_auth.del_app(args[1]) else "Not Found."
        raise _Usage()


class _Usage(Exception):
    pass


async def main(argv: Optional[list[str]] = None) -> str:
    """`python -m emqx_tpu.mgmt.cli <cmd> ...` against a local dev node."""
    import sys

    from emqx_tpu.broker.node import Node
    node = Node(use_device=False)
    cli = Cli(node)
    out = await cli.run(argv if argv is not None else sys.argv[1:])
    print(out)
    return out


if __name__ == "__main__":
    import asyncio
    import sys
    asyncio.run(main(sys.argv[1:]))
