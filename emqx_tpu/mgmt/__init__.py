"""Management: REST API + CLI + cross-node fan-out.

Parity: apps/emqx_management — emqx_mgmt.erl (facade), emqx_mgmt_http/
emqx_mgmt_api_*.erl (REST over minirest), emqx_mgmt_cli.erl (emqx_ctl
commands), emqx_mgmt_auth.erl (app id/secret credentials).
"""

from emqx_tpu.mgmt.api import make_api
from emqx_tpu.mgmt.cli import Cli
from emqx_tpu.mgmt.httpd import HttpServer
from emqx_tpu.mgmt.mgmt import Mgmt

__all__ = ["Mgmt", "make_api", "HttpServer", "Cli"]
