"""LDAP v3 connector: minimal BER codec + asyncio client.

Parity: apps/emqx_connector/src/emqx_connector_ldap.erl (eldap). Covers
what broker integrations use: simple bind, equality/present search with
AND conjunctions, unbind — RFC 4511 over BER with definite lengths.
"""

from __future__ import annotations

import asyncio
from typing import Optional, Union

# application tags
AP_BIND_REQ, AP_BIND_RESP = 0, 1
AP_UNBIND = 2
AP_SEARCH_REQ, AP_SEARCH_ENTRY, AP_SEARCH_DONE = 3, 4, 5

SCOPE_BASE, SCOPE_ONE, SCOPE_SUB = 0, 1, 2


class LdapError(Exception):
    def __init__(self, code: int, message: str = ""):
        self.code = code
        super().__init__(f"ldap error {code}: {message}")


# ---------------------------------------------------------------------------
# BER (definite length)
# ---------------------------------------------------------------------------

def _len(n: int) -> bytes:
    if n < 0x80:
        return bytes([n])
    body = n.to_bytes((n.bit_length() + 7) // 8, "big")
    return bytes([0x80 | len(body)]) + body


def tlv(tag: int, body: bytes) -> bytes:
    return bytes([tag]) + _len(len(body)) + body


def ber_int(v: int, tag: int = 0x02) -> bytes:
    if v == 0:
        return tlv(tag, b"\x00")
    out = v.to_bytes((v.bit_length() // 8) + 1, "big")
    return tlv(tag, out)


def ber_str(s: Union[str, bytes], tag: int = 0x04) -> bytes:
    return tlv(tag, s if isinstance(s, bytes) else s.encode())


def ber_bool(v: bool) -> bytes:
    return tlv(0x01, b"\xff" if v else b"\x00")


def ber_seq(*parts: bytes) -> bytes:
    return tlv(0x30, b"".join(parts))


def read_tlv(data: bytes, pos: int) -> tuple[int, bytes, int]:
    """-> (tag, body, next_pos)."""
    tag = data[pos]
    ln = data[pos + 1]
    pos += 2
    if ln & 0x80:
        n = ln & 0x7F
        ln = int.from_bytes(data[pos:pos + n], "big")
        pos += n
    return tag, data[pos:pos + ln], pos + ln


def read_int(body: bytes) -> int:
    return int.from_bytes(body, "big", signed=True)


# filter builders (the subset authn/authz templates produce)
def f_eq(attr: str, value: str) -> bytes:
    return tlv(0xA3, ber_str(attr) + ber_str(value))


def f_present(attr: str) -> bytes:
    return ber_str(attr, tag=0x87)


def f_and(*filters: bytes) -> bytes:
    return tlv(0xA0, b"".join(filters))


class LdapClient:
    def __init__(self, host: str = "127.0.0.1", port: int = 389,
                 bind_dn: str = "", bind_password: str = "", ssl=None,
                 connect_timeout: float = 5.0):
        self.host = host
        self.port = port
        self.bind_dn = bind_dn
        self.bind_password = bind_password
        self.ssl = ssl
        self.connect_timeout = connect_timeout
        self._r: Optional[asyncio.StreamReader] = None
        self._w: Optional[asyncio.StreamWriter] = None
        self._mid = 0

    async def connect(self) -> None:
        self._r, self._w = await asyncio.wait_for(
            asyncio.open_connection(self.host, self.port, ssl=self.ssl),
            self.connect_timeout)
        try:
            await self.bind(self.bind_dn, self.bind_password)
        except BaseException:
            # a failed bind must not leak the socket (pool retries would
            # pile up half-open server sessions)
            self._w.close()
            self._r = self._w = None
            raise

    async def close(self) -> None:
        if self._w is not None:
            try:
                self._mid += 1
                self._w.write(ber_seq(ber_int(self._mid),
                                      tlv(0x42, b"")))       # unbind
                await self._w.drain()
            except Exception:  # noqa: BLE001
                pass
            self._w.close()
            try:
                await self._w.wait_closed()
            except Exception:  # noqa: BLE001
                pass
            self._r = self._w = None

    async def _read_message(self) -> tuple[int, int, bytes]:
        """-> (message_id, op_tag, op_body)."""
        head = await self._r.readexactly(2)
        ln = head[1]
        extra = b""
        if ln & 0x80:
            extra = await self._r.readexactly(ln & 0x7F)
            ln = int.from_bytes(extra, "big")
        body = await self._r.readexactly(ln)
        _tag, mid_body, pos = read_tlv(body, 0)
        op_tag, op_body, _ = read_tlv(body, pos)
        return read_int(mid_body), op_tag, op_body

    @staticmethod
    def _result(op_body: bytes) -> tuple[int, str]:
        _t, code, pos = read_tlv(op_body, 0)
        _t, _dn, pos = read_tlv(op_body, pos)
        _t, diag, _ = read_tlv(op_body, pos)
        return read_int(code), diag.decode("utf-8", "replace")

    async def bind(self, dn: str, password: str) -> None:
        self._mid += 1
        op = tlv(0x60, ber_int(3) + ber_str(dn)
                 + ber_str(password, tag=0x80))
        self._w.write(ber_seq(ber_int(self._mid), op))
        await self._w.drain()
        _mid, tag, body = await self._read_message()
        if tag != 0x61:
            raise LdapError(-1, f"unexpected response tag {tag:#x}")
        code, diag = self._result(body)
        if code != 0:
            raise LdapError(code, diag or "bind failed")

    async def ping(self) -> bool:
        # RootDSE base search is the conventional liveness probe
        await self.search("", SCOPE_BASE, f_present("objectClass"),
                          attributes=["objectClass"], size_limit=1)
        return True

    async def search(self, base_dn: str, scope: int, filt: bytes,
                     attributes: Optional[list[str]] = None,
                     size_limit: int = 0) -> list[dict]:
        """-> [{"dn": ..., "<attr>": [values...]}]."""
        if self._w is None:
            raise ConnectionError("ldap client not connected")
        self._mid += 1
        attrs = ber_seq(*[ber_str(a) for a in (attributes or [])])
        op = tlv(0x63, ber_str(base_dn) + ber_int(scope, tag=0x0A)
                 + ber_int(0, tag=0x0A) + ber_int(size_limit) + ber_int(0)
                 + ber_bool(False) + filt + attrs)
        self._w.write(ber_seq(ber_int(self._mid), op))
        await self._w.drain()
        out: list[dict] = []
        while True:
            _mid, tag, body = await self._read_message()
            if tag == 0x64:                              # SearchResultEntry
                _t, dn, pos = read_tlv(body, 0)
                entry: dict = {"dn": dn.decode("utf-8", "replace")}
                _t, attrs_body, _ = read_tlv(body, pos)
                apos = 0
                while apos < len(attrs_body):
                    _t, attr_seq, apos = read_tlv(attrs_body, apos)
                    _t, name, vpos = read_tlv(attr_seq, 0)
                    _t, vals_set, _ = read_tlv(attr_seq, vpos)
                    vals, spos = [], 0
                    while spos < len(vals_set):
                        _t, v, spos = read_tlv(vals_set, spos)
                        vals.append(v.decode("utf-8", "replace"))
                    entry[name.decode()] = vals
                out.append(entry)
            elif tag == 0x65:                            # SearchResultDone
                code, diag = self._result(body)
                if code not in (0, 4):                   # 4 = sizeLimit
                    raise LdapError(code, diag)
                return out
            else:
                raise LdapError(-1, f"unexpected response tag {tag:#x}")
