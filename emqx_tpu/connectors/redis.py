"""Redis connector: RESP2 protocol over asyncio.

Parity: apps/emqx_connector/src/emqx_connector_redis.erl (eredis/ecpool —
single/sentinel/cluster modes; round-2 VERDICT missing #6). `RedisClient`
is the single-server client; `SentinelRedisClient` resolves the current
master through a list of sentinels (SENTINEL get-master-addr-by-name),
verifies the target's role, and re-resolves on reconnect —
eredis_sentinel's behavior. `ClusterRedisClient` routes by CRC16 hash
slot over a CLUSTER SLOTS topology with MOVED/ASK redirect handling —
eredis_cluster's behavior.
"""

from __future__ import annotations

import asyncio
from typing import Optional, Union

Arg = Union[str, bytes, int, float]


class RedisError(Exception):
    pass


class RedisClient:
    def __init__(self, host: str = "127.0.0.1", port: int = 6379,
                 password: Optional[str] = None,
                 username: Optional[str] = None,
                 database: int = 0, ssl=None,
                 connect_timeout: float = 5.0):
        self.host = host
        self.port = port
        self.password = password
        self.username = username
        self.database = database
        self.ssl = ssl
        self.connect_timeout = connect_timeout
        self._r: Optional[asyncio.StreamReader] = None
        self._w: Optional[asyncio.StreamWriter] = None

    async def connect(self) -> None:
        self._r, self._w = await asyncio.wait_for(
            asyncio.open_connection(self.host, self.port, ssl=self.ssl),
            self.connect_timeout)
        try:
            if self.password:
                if self.username:
                    await self.cmd(["AUTH", self.username, self.password])
                else:
                    await self.cmd(["AUTH", self.password])
            if self.database:
                await self.cmd(["SELECT", str(self.database)])
        except BaseException:
            self._w.close()         # auth failure must not leak the socket
            self._r = self._w = None
            raise

    async def close(self) -> None:
        if self._w is not None:
            self._w.close()
            try:
                await self._w.wait_closed()
            except Exception:  # noqa: BLE001
                pass
            self._r = self._w = None

    async def ping(self) -> bool:
        return await self.cmd(["PING"]) == b"PONG"

    # ---- RESP codec ----
    @staticmethod
    def _encode(args: list[Arg]) -> bytes:
        out = [b"*%d\r\n" % len(args)]
        for a in args:
            b = a if isinstance(a, bytes) else str(a).encode()
            out.append(b"$%d\r\n%s\r\n" % (len(b), b))
        return b"".join(out)

    async def _read_reply(self):
        line = (await self._r.readuntil(b"\r\n"))[:-2]
        kind, rest = line[:1], line[1:]
        if kind == b"+":
            return rest
        if kind == b"-":
            raise RedisError(rest.decode())
        if kind == b":":
            return int(rest)
        if kind == b"$":
            n = int(rest)
            if n == -1:
                return None
            data = await self._r.readexactly(n + 2)
            return data[:-2]
        if kind == b"*":
            n = int(rest)
            if n == -1:
                return None
            return [await self._read_reply() for _ in range(n)]
        raise RedisError(f"bad RESP type byte {kind!r}")

    async def cmd(self, args: list[Arg]):
        """One command -> decoded reply (bytes / int / list / None)."""
        if self._w is None:
            raise ConnectionError("redis client not connected")
        self._w.write(self._encode(args))
        await self._w.drain()
        return await self._read_reply()


class SentinelRedisClient(RedisClient):
    """Redis via sentinel: each (re)connect asks the sentinels for the
    master of `master_name`, connects there, and verifies ROLE == master
    (eredis_sentinel's guard against stale sentinel answers during a
    failover). Pool reconnects (ConnPool) therefore follow the failover
    automatically: the next connect() re-resolves.

    sentinels: list of (host, port) pairs, tried in order.
    """

    def __init__(self, sentinels: list, master_name: str = "mymaster",
                 password: Optional[str] = None,
                 username: Optional[str] = None,
                 sentinel_password: Optional[str] = None,
                 database: int = 0, ssl=None,
                 connect_timeout: float = 5.0):
        super().__init__(host="", port=0, password=password,
                         username=username, database=database, ssl=ssl,
                         connect_timeout=connect_timeout)
        self.sentinels = list(sentinels)
        self.master_name = master_name
        self.sentinel_password = sentinel_password

    async def _resolve_master(self) -> tuple[str, int]:
        last: Optional[Exception] = None
        for host, port in self.sentinels:
            s = RedisClient(host=host, port=port,
                            password=self.sentinel_password,
                            ssl=self.ssl,
                            connect_timeout=self.connect_timeout)
            try:
                await s.connect()
                reply = await s.cmd(["SENTINEL", "get-master-addr-by-name",
                                     self.master_name])
                if reply and len(reply) == 2:
                    return reply[0].decode(), int(reply[1])
                last = RedisError(
                    f"sentinel {host}:{port} has no master "
                    f"{self.master_name!r}")
            except (OSError, RedisError, asyncio.TimeoutError) as e:
                last = e
            finally:
                await s.close()
        raise RedisError(f"no sentinel could resolve master "
                         f"{self.master_name!r}: {last}")

    async def connect(self) -> None:
        self.host, self.port = await self._resolve_master()
        await super().connect()
        role = await self.cmd(["ROLE"])
        if not (role and role[0] == b"master"):
            await self.close()
            raise RedisError(
                f"{self.host}:{self.port} is not master (failover in "
                f"progress?) — will re-resolve on next connect")


# ---- cluster mode (eredis_cluster parity) -------------------------------

# CRC16-CCITT (XMODEM) table, the hash-slot function Redis specifies
_CRC16_TAB = []
for _i in range(256):
    _c = _i << 8
    for _ in range(8):
        _c = ((_c << 1) ^ 0x1021) if _c & 0x8000 else (_c << 1)
    _CRC16_TAB.append(_c & 0xFFFF)


def crc16(data: bytes) -> int:
    c = 0
    for b in data:
        c = ((c << 8) & 0xFFFF) ^ _CRC16_TAB[((c >> 8) ^ b) & 0xFF]
    return c


def key_slot(key: Union[str, bytes]) -> int:
    """Hash slot of a key: CRC16 % 16384, honoring {hash tags} — only the
    substring between the first '{' and the next '}' hashes when that
    substring is non-empty (the Redis cluster spec's tag rule)."""
    k = key.encode() if isinstance(key, str) else key
    lo = k.find(b"{")
    if lo >= 0:
        hi = k.find(b"}", lo + 1)
        if hi > lo + 1:
            k = k[lo + 1:hi]
    return crc16(k) % 16384


# commands without a key argument route to any node
_KEYLESS = {b"PING", b"INFO", b"CLUSTER", b"COMMAND", b"AUTH", b"SELECT"}


class ClusterRedisClient:
    """Redis cluster client: one connection per master node, commands
    routed by the slot of their first key. MOVED replies refresh the
    topology and retry; ASK replies follow the redirect once with an
    ASKING prefix (slot migration in progress). Bounded redirects, so a
    flapping cluster errors instead of looping.
    """

    MAX_REDIRECTS = 5

    def __init__(self, startup_nodes: list, password: Optional[str] = None,
                 username: Optional[str] = None, ssl=None,
                 connect_timeout: float = 5.0):
        self.startup_nodes = [(h, int(p)) for h, p in startup_nodes]
        self.password = password
        self.username = username
        self.ssl = ssl
        self.connect_timeout = connect_timeout
        self._conns: dict[tuple, RedisClient] = {}
        # sorted (start, end, (host, port)) ranges from CLUSTER SLOTS
        self._ranges: list[tuple] = []

    def _new_client(self, host: str, port: int) -> RedisClient:
        return RedisClient(host=host, port=port, password=self.password,
                           username=self.username, ssl=self.ssl,
                           connect_timeout=self.connect_timeout)

    async def _conn(self, addr: tuple) -> RedisClient:
        c = self._conns.get(addr)
        if c is None or c._w is None:
            c = self._new_client(*addr)
            await c.connect()
            self._conns[addr] = c
        return c

    async def _drop_conn(self, addr: tuple) -> None:
        c = self._conns.pop(addr, None)
        if c is not None:
            await c.close()

    async def refresh_topology(self) -> None:
        """CLUSTER SLOTS from the first reachable node (connected nodes
        first, then startup nodes)."""
        last: Optional[Exception] = None
        seeds = list(self._conns) + [a for a in self.startup_nodes
                                     if a not in self._conns]
        for addr in seeds:
            try:
                c = await self._conn(addr)
                # connect_timeout only bounds the TCP handshake: a
                # half-open seed must not hang the probe forever
                slots = await asyncio.wait_for(
                    c.cmd(["CLUSTER", "SLOTS"]), self.connect_timeout)
                ranges = []
                for entry in slots or []:
                    start, end, master = entry[0], entry[1], entry[2]
                    host = master[0].decode() if isinstance(master[0], bytes) \
                        else str(master[0])
                    ranges.append((int(start), int(end),
                                   (host, int(master[1]))))
                if not ranges:
                    raise RedisError(f"{addr} returned empty CLUSTER SLOTS")
                ranges.sort()
                self._ranges = ranges
                return
            except (OSError, RedisError, asyncio.TimeoutError,
                    ConnectionError, asyncio.IncompleteReadError) as e:
                last = e
                await self._drop_conn(addr)
        raise RedisError(f"no cluster node reachable for topology: {last}")

    async def connect(self) -> None:
        await self.refresh_topology()

    async def close(self) -> None:
        for addr in list(self._conns):
            await self._drop_conn(addr)

    def _addr_for_slot(self, slot: int) -> tuple:
        for start, end, addr in self._ranges:
            if start <= slot <= end:
                return addr
        raise RedisError(f"no node serves slot {slot} (topology stale)")

    @staticmethod
    def _command_key(args: list) -> Optional[bytes]:
        if not args:
            return None
        cmd = args[0]
        cmd = cmd.upper() if isinstance(cmd, bytes) else str(cmd).upper()
        if (cmd if isinstance(cmd, bytes) else cmd.encode()) in _KEYLESS \
                or len(args) < 2:
            return None
        k = args[1]
        return k if isinstance(k, bytes) else str(k).encode()

    async def ping(self) -> bool:
        if not self._ranges:
            await self.refresh_topology()
        c = await self._conn(self._ranges[0][2])
        return await c.cmd(["PING"]) == b"PONG"

    async def cmd(self, args: list, key: Optional[Union[str, bytes]] = None):
        """One command, routed by `key` (default: the first key argument).
        Follows MOVED (with topology refresh) and ASK redirects."""
        if not self._ranges:
            await self.refresh_topology()
        k = (key.encode() if isinstance(key, str) else key) \
            if key is not None else self._command_key(args)
        try:
            addr = self._addr_for_slot(key_slot(k)) if k is not None \
                else self._ranges[0][2]
        except RedisError:
            # slot gap (map captured mid-reshard): refresh once before
            # giving up, else the slot fails until an unrelated refresh
            await self.refresh_topology()
            addr = self._addr_for_slot(key_slot(k)) if k is not None \
                else self._ranges[0][2]
        asking = False
        last: Optional[Exception] = None
        for _ in range(self.MAX_REDIRECTS + 1):
            try:
                c = await self._conn(addr)
                if asking:
                    await c.cmd(["ASKING"])
                    asking = False
                return await c.cmd(args)
            except RedisError as e:
                msg = str(e)
                if msg.startswith("MOVED ") or msg.startswith("ASK "):
                    kind, _slot, hp = msg.split(" ", 2)
                    host, _, port = hp.rpartition(":")
                    addr = (host, int(port))
                    if kind == "MOVED":
                        # ownership changed: refetch the full map (a MOVED
                        # storm during resharding collapses to one refresh)
                        try:
                            await self.refresh_topology()
                        except RedisError:
                            pass     # still follow the explicit redirect
                    else:
                        asking = True
                    last = e
                    continue
                raise
            except (OSError, ConnectionError, asyncio.TimeoutError,
                    asyncio.IncompleteReadError) as e:
                # node died: drop the conn, refresh, re-route by slot
                await self._drop_conn(addr)
                await self.refresh_topology()
                addr = self._addr_for_slot(key_slot(k)) if k is not None \
                    else self._ranges[0][2]
                last = e
        raise RedisError(f"too many cluster redirects: {last}")
