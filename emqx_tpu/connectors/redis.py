"""Redis connector: RESP2 protocol over asyncio.

Parity: apps/emqx_connector/src/emqx_connector_redis.erl (eredis/ecpool —
single/sentinel modes; round-2 VERDICT missing #6). `RedisClient` is the
single-server client; `SentinelRedisClient` resolves the current master
through a list of sentinels (SENTINEL get-master-addr-by-name), verifies
the target's role, and re-resolves on reconnect — eredis_sentinel's
behavior. Cluster mode (slot routing) remains out of scope for the
broker's authz/rule use and is documented as such.
"""

from __future__ import annotations

import asyncio
from typing import Optional, Union

Arg = Union[str, bytes, int, float]


class RedisError(Exception):
    pass


class RedisClient:
    def __init__(self, host: str = "127.0.0.1", port: int = 6379,
                 password: Optional[str] = None,
                 username: Optional[str] = None,
                 database: int = 0, ssl=None,
                 connect_timeout: float = 5.0):
        self.host = host
        self.port = port
        self.password = password
        self.username = username
        self.database = database
        self.ssl = ssl
        self.connect_timeout = connect_timeout
        self._r: Optional[asyncio.StreamReader] = None
        self._w: Optional[asyncio.StreamWriter] = None

    async def connect(self) -> None:
        self._r, self._w = await asyncio.wait_for(
            asyncio.open_connection(self.host, self.port, ssl=self.ssl),
            self.connect_timeout)
        try:
            if self.password:
                if self.username:
                    await self.cmd(["AUTH", self.username, self.password])
                else:
                    await self.cmd(["AUTH", self.password])
            if self.database:
                await self.cmd(["SELECT", str(self.database)])
        except BaseException:
            self._w.close()         # auth failure must not leak the socket
            self._r = self._w = None
            raise

    async def close(self) -> None:
        if self._w is not None:
            self._w.close()
            try:
                await self._w.wait_closed()
            except Exception:  # noqa: BLE001
                pass
            self._r = self._w = None

    async def ping(self) -> bool:
        return await self.cmd(["PING"]) == b"PONG"

    # ---- RESP codec ----
    @staticmethod
    def _encode(args: list[Arg]) -> bytes:
        out = [b"*%d\r\n" % len(args)]
        for a in args:
            b = a if isinstance(a, bytes) else str(a).encode()
            out.append(b"$%d\r\n%s\r\n" % (len(b), b))
        return b"".join(out)

    async def _read_reply(self):
        line = (await self._r.readuntil(b"\r\n"))[:-2]
        kind, rest = line[:1], line[1:]
        if kind == b"+":
            return rest
        if kind == b"-":
            raise RedisError(rest.decode())
        if kind == b":":
            return int(rest)
        if kind == b"$":
            n = int(rest)
            if n == -1:
                return None
            data = await self._r.readexactly(n + 2)
            return data[:-2]
        if kind == b"*":
            n = int(rest)
            if n == -1:
                return None
            return [await self._read_reply() for _ in range(n)]
        raise RedisError(f"bad RESP type byte {kind!r}")

    async def cmd(self, args: list[Arg]):
        """One command -> decoded reply (bytes / int / list / None)."""
        if self._w is None:
            raise ConnectionError("redis client not connected")
        self._w.write(self._encode(args))
        await self._w.drain()
        return await self._read_reply()


class SentinelRedisClient(RedisClient):
    """Redis via sentinel: each (re)connect asks the sentinels for the
    master of `master_name`, connects there, and verifies ROLE == master
    (eredis_sentinel's guard against stale sentinel answers during a
    failover). Pool reconnects (ConnPool) therefore follow the failover
    automatically: the next connect() re-resolves.

    sentinels: list of (host, port) pairs, tried in order.
    """

    def __init__(self, sentinels: list, master_name: str = "mymaster",
                 password: Optional[str] = None,
                 username: Optional[str] = None,
                 sentinel_password: Optional[str] = None,
                 database: int = 0, ssl=None,
                 connect_timeout: float = 5.0):
        super().__init__(host="", port=0, password=password,
                         username=username, database=database, ssl=ssl,
                         connect_timeout=connect_timeout)
        self.sentinels = list(sentinels)
        self.master_name = master_name
        self.sentinel_password = sentinel_password

    async def _resolve_master(self) -> tuple[str, int]:
        last: Optional[Exception] = None
        for host, port in self.sentinels:
            s = RedisClient(host=host, port=port,
                            password=self.sentinel_password,
                            ssl=self.ssl,
                            connect_timeout=self.connect_timeout)
            try:
                await s.connect()
                reply = await s.cmd(["SENTINEL", "get-master-addr-by-name",
                                     self.master_name])
                if reply and len(reply) == 2:
                    return reply[0].decode(), int(reply[1])
                last = RedisError(
                    f"sentinel {host}:{port} has no master "
                    f"{self.master_name!r}")
            except (OSError, RedisError, asyncio.TimeoutError) as e:
                last = e
            finally:
                await s.close()
        raise RedisError(f"no sentinel could resolve master "
                         f"{self.master_name!r}: {last}")

    async def connect(self) -> None:
        self.host, self.port = await self._resolve_master()
        await super().connect()
        role = await self.cmd(["ROLE"])
        if not (role and role[0] == b"master"):
            await self.close()
            raise RedisError(
                f"{self.host}:{self.port} is not master (failover in "
                f"progress?) — will re-resolve on next connect")
