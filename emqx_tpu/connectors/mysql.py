"""MySQL connector: client/server protocol v10 over asyncio.

Parity: apps/emqx_connector/src/emqx_connector_mysql.erl (mysql-otp).
Implements the handshake with both `mysql_native_password` and
`caching_sha2_password` (MySQL 8's default — fast path and full path via
RSA public-key exchange, round-2 VERDICT missing #2), COM_QUERY text
resultsets, COM_PING, and server-side prepared statements
(COM_STMT_PREPARE/EXECUTE, binary resultsets). Parameterized queries go
through the prepared path like mysql-otp — parameters never enter the SQL
text, so no client-side escaping can be subverted by sql_mode
NO_BACKSLASH_ESCAPES (ADVICE round-2).
"""

from __future__ import annotations

import asyncio
import hashlib
import struct
from typing import Any, Optional

CLIENT_LONG_PASSWORD = 0x1
CLIENT_PROTOCOL_41 = 0x200
CLIENT_SECURE_CONNECTION = 0x8000
CLIENT_PLUGIN_AUTH = 0x80000
CLIENT_CONNECT_WITH_DB = 0x8
CLIENT_TRANSACTIONS = 0x2000


class MysqlError(Exception):
    def __init__(self, code: int, msg: str):
        self.code = code
        super().__init__(f"mysql error {code}: {msg}")


def _native_scramble(password: bytes, nonce: bytes) -> bytes:
    """SHA1(pw) XOR SHA1(nonce + SHA1(SHA1(pw))) — mysql_native_password."""
    if not password:
        return b""
    h1 = hashlib.sha1(password).digest()
    h2 = hashlib.sha1(h1).digest()
    h3 = hashlib.sha1(nonce + h2).digest()
    return bytes(a ^ b for a, b in zip(h1, h3))


def _sha2_scramble(password: bytes, nonce: bytes) -> bytes:
    """XOR(SHA256(pw), SHA256(SHA256(SHA256(pw)) + nonce)) —
    caching_sha2_password fast-path token."""
    if not password:
        return b""
    h1 = hashlib.sha256(password).digest()
    h2 = hashlib.sha256(hashlib.sha256(h1).digest() + nonce).digest()
    return bytes(a ^ b for a, b in zip(h1, h2))


def _rsa_encrypt_password(password: bytes, nonce: bytes,
                          pubkey_pem: bytes) -> bytes:
    """caching_sha2 full path over a plain connection: XOR the
    NUL-terminated password with the nonce and RSA-OAEP(SHA1) it under
    the server's public key."""
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import padding
    pw = password + b"\x00"
    xored = bytes(b ^ nonce[i % len(nonce)] for i, b in enumerate(pw))
    key = serialization.load_pem_public_key(pubkey_pem)
    return key.encrypt(xored, padding.OAEP(
        mgf=padding.MGF1(hashes.SHA1()), algorithm=hashes.SHA1(),
        label=None))


def _lenenc(data: bytes, pos: int) -> tuple[Optional[int], int]:
    first = data[pos]
    if first < 0xFB:
        return first, pos + 1
    if first == 0xFB:
        return None, pos + 1                       # NULL
    if first == 0xFC:
        return struct.unpack_from("<H", data, pos + 1)[0], pos + 3
    if first == 0xFD:
        return int.from_bytes(data[pos + 1:pos + 4], "little"), pos + 4
    return struct.unpack_from("<Q", data, pos + 1)[0], pos + 9


def _enc_lenenc(b: bytes) -> bytes:
    n = len(b)
    if n < 251:
        return bytes([n]) + b
    if n < 1 << 16:
        return b"\xfc" + struct.pack("<H", n) + b
    if n < 1 << 24:
        return b"\xfd" + n.to_bytes(3, "little") + b
    return b"\xfe" + struct.pack("<Q", n) + b


def _decode_binary_row(pkt: bytes, ncols: int,
                       col_types: list[int]) -> list:
    """Binary-protocol resultset row -> text-compatible values (str/None,
    matching what the text path returns for the same data)."""
    pos = 1                                       # 0x00 header
    nbm = (ncols + 9) // 8
    bitmap = pkt[pos:pos + nbm]
    pos += nbm
    row: list = []
    for i in range(ncols):
        if bitmap[(i + 2) // 8] & (1 << ((i + 2) % 8)):
            row.append(None)
            continue
        t = col_types[i]
        if t in (0x01,):                          # TINY
            row.append(str(struct.unpack_from("<b", pkt, pos)[0]))
            pos += 1
        elif t in (0x02, 0x0D):                   # SHORT / YEAR
            row.append(str(struct.unpack_from("<h", pkt, pos)[0]))
            pos += 2
        elif t in (0x03, 0x09):                   # LONG / INT24
            row.append(str(struct.unpack_from("<i", pkt, pos)[0]))
            pos += 4
        elif t == 0x08:                           # LONGLONG
            row.append(str(struct.unpack_from("<q", pkt, pos)[0]))
            pos += 8
        elif t == 0x04:                           # FLOAT
            row.append(repr(struct.unpack_from("<f", pkt, pos)[0]))
            pos += 4
        elif t == 0x05:                           # DOUBLE
            row.append(repr(struct.unpack_from("<d", pkt, pos)[0]))
            pos += 8
        elif t in (0x07, 0x0A, 0x0C):             # TIMESTAMP/DATE/DATETIME
            n = pkt[pos]
            pos += 1
            v, pos = _decode_bin_datetime(pkt, pos, n, date_only=(t == 0x0A))
            row.append(v)
        elif t == 0x0B:                           # TIME
            n = pkt[pos]
            pos += 1
            v, pos = _decode_bin_time(pkt, pos, n)
            row.append(v)
        else:                                     # lenenc (strings/blobs/
            n, pos = _lenenc(pkt, pos)            #  decimals/json)
            row.append(pkt[pos:pos + (n or 0)].decode("utf-8", "replace"))
            pos += n or 0
    return row


def _decode_bin_datetime(pkt: bytes, pos: int, n: int,
                         date_only: bool) -> tuple[str, int]:
    """Binary DATE/DATETIME/TIMESTAMP payload (length n in 0/4/7/11) ->
    the text-protocol rendering, so prepared and text paths agree."""
    y = mo = d = h = mi = s = us = 0
    if n >= 4:
        y, mo, d = struct.unpack_from("<HBB", pkt, pos)
    if n >= 7:
        h, mi, s = struct.unpack_from("<BBB", pkt, pos + 4)
    if n >= 11:
        us = struct.unpack_from("<I", pkt, pos + 7)[0]
    if date_only:
        out = f"{y:04d}-{mo:02d}-{d:02d}"
    else:
        out = f"{y:04d}-{mo:02d}-{d:02d} {h:02d}:{mi:02d}:{s:02d}"
        if us:
            out += f".{us:06d}"
    return out, pos + n


def _decode_bin_time(pkt: bytes, pos: int, n: int) -> tuple[str, int]:
    """Binary TIME payload (length n in 0/8/12): sign, days, h:m:s[.us]."""
    neg = days = h = mi = s = us = 0
    if n >= 8:
        neg, days, h, mi, s = struct.unpack_from("<BIBBB", pkt, pos)
    if n >= 12:
        us = struct.unpack_from("<I", pkt, pos + 8)[0]
    out = f"{'-' if neg else ''}{days * 24 + h:02d}:{mi:02d}:{s:02d}"
    if us:
        out += f".{us:06d}"
    return out, pos + n


def escape(value: Any) -> str:
    """SQL-literal encoding of a parameter (client-side prepared stmt)."""
    if value is None:
        return "NULL"
    if isinstance(value, bool):
        return "1" if value else "0"
    if isinstance(value, (int, float)):
        return str(value)
    if isinstance(value, (bytes, bytearray)):
        return "x'" + bytes(value).hex() + "'"
    s = str(value)
    s = (s.replace("\\", "\\\\").replace("'", "\\'")
          .replace("\x00", "\\0").replace("\n", "\\n").replace("\r", "\\r")
          .replace("\x1a", "\\Z"))
    return f"'{s}'"


def bind_params(query: str, params: list) -> str:
    parts = query.split("?")
    if len(parts) - 1 != len(params):
        raise ValueError(f"query expects {len(parts)-1} params, "
                         f"got {len(params)}")
    out = [parts[0]]
    for val, tail in zip(params, parts[1:]):
        out.append(escape(val))
        out.append(tail)
    return "".join(out)


class MysqlClient:
    def __init__(self, host: str = "127.0.0.1", port: int = 3306,
                 username: str = "root", password: str = "",
                 database: Optional[str] = None, ssl=None,
                 connect_timeout: float = 5.0):
        self.host = host
        self.port = port
        self.username = username
        self.password = password
        self.database = database
        self.ssl = ssl
        self.connect_timeout = connect_timeout
        self._r: Optional[asyncio.StreamReader] = None
        self._w: Optional[asyncio.StreamWriter] = None
        self._seq = 0

    # ---- packet framing: 3-byte length + sequence id ----
    async def _read_packet(self) -> bytes:
        head = await self._r.readexactly(4)
        n = int.from_bytes(head[:3], "little")
        self._seq = (head[3] + 1) & 0xFF
        return await self._r.readexactly(n)

    def _write_packet(self, payload: bytes) -> None:
        self._w.write(len(payload).to_bytes(3, "little")
                      + bytes([self._seq]) + payload)
        self._seq = (self._seq + 1) & 0xFF

    @staticmethod
    def _err(payload: bytes) -> MysqlError:
        code = struct.unpack_from("<H", payload, 1)[0]
        msg = payload[3:].decode("utf-8", "replace")
        if msg.startswith("#"):       # SQL-state marker
            msg = msg[6:]
        return MysqlError(code, msg)

    async def connect(self) -> None:
        self._r, self._w = await asyncio.wait_for(
            asyncio.open_connection(self.host, self.port, ssl=self.ssl),
            self.connect_timeout)
        try:
            await self._handshake()
        except BaseException:
            self._w.close()     # auth failure must not leak the socket
            self._r = self._w = None
            raise

    def _auth_token(self, plugin: str, nonce: bytes) -> bytes:
        pw = self.password.encode()
        if plugin == "caching_sha2_password":
            return _sha2_scramble(pw, nonce)
        if plugin == "mysql_native_password":
            return _native_scramble(pw, nonce)
        raise MysqlError(0, f"unsupported auth plugin {plugin}")

    async def _handshake(self) -> None:
        greet = await self._read_packet()
        if greet[:1] == b"\xff":
            raise self._err(greet)
        pos = 1
        end = greet.index(b"\x00", pos)         # server version string
        pos = end + 1 + 4                       # thread id
        nonce1 = greet[pos:pos + 8]
        pos += 8 + 1                            # filler
        pos += 2 + 1 + 2 + 2                    # caps-lo, charset, status,
        auth_len = greet[pos] if pos < len(greet) else 0   # caps-hi read ^
        pos += 1 + 10
        nonce2 = b""
        if auth_len:
            # part-2 is auth_len-8 bytes including a trailing NUL; the
            # scramble uses exactly 20 nonce bytes total
            nonce2 = greet[pos:pos + max(0, auth_len - 9)]
            pos += max(0, auth_len - 8)
        nonce = (nonce1 + nonce2)[:20]
        # server's advertised auth plugin (NUL-terminated tail)
        plugin = "mysql_native_password"
        tail = greet[pos:]
        if tail:
            plugin = tail.split(b"\x00", 1)[0].decode() or plugin

        caps = (CLIENT_LONG_PASSWORD | CLIENT_PROTOCOL_41 |
                CLIENT_SECURE_CONNECTION | CLIENT_PLUGIN_AUTH |
                CLIENT_TRANSACTIONS)
        if self.database:
            caps |= CLIENT_CONNECT_WITH_DB
        auth = self._auth_token(plugin, nonce)
        resp = struct.pack("<IIB23x", caps, 1 << 24, 0x21)  # utf8_general_ci
        resp += self.username.encode() + b"\x00"
        resp += bytes([len(auth)]) + auth
        if self.database:
            resp += self.database.encode() + b"\x00"
        resp += plugin.encode() + b"\x00"
        self._write_packet(resp)
        await self._auth_loop(plugin, nonce)

    async def _auth_loop(self, plugin: str, nonce: bytes) -> None:
        """Drive AuthSwitch / AuthMoreData until OK (or error). Covers the
        caching_sha2 fast path (0x03) and full path (0x04: cleartext over
        TLS, RSA public-key exchange over plain TCP)."""
        while True:
            reply = await self._read_packet()
            tag = reply[:1]
            if tag == b"\x00":                   # OK
                return
            if tag == b"\xff":
                raise self._err(reply)
            if tag == b"\xfe":                   # AuthSwitchRequest
                end = reply.index(b"\x00", 1)
                plugin = reply[1:end].decode()
                nonce = reply[end + 1:]
                if nonce.endswith(b"\x00"):   # strip ONLY the terminator —
                    nonce = nonce[:-1]        # scramble bytes may be 0x00
                self._write_packet(self._auth_token(plugin, nonce))
                await self._w.drain()
                continue
            if tag == b"\x01":                   # AuthMoreData
                more = reply[1:]
                if plugin != "caching_sha2_password":
                    raise MysqlError(0, f"unexpected AuthMoreData under "
                                        f"{plugin}")
                if more == b"\x03":              # fast auth success
                    continue                     # OK packet follows
                if more == b"\x04":              # full authentication
                    if self.ssl is not None:
                        # channel is already encrypted: cleartext password
                        self._write_packet(self.password.encode() + b"\x00")
                    else:
                        # request the server RSA public key, then send the
                        # nonce-XORed password OAEP-encrypted under it
                        self._write_packet(b"\x02")
                        await self._w.drain()
                        keypkt = await self._read_packet()
                        if keypkt[:1] != b"\x01":
                            raise MysqlError(0, "expected server public key")
                        self._write_packet(_rsa_encrypt_password(
                            self.password.encode(), nonce, keypkt[1:]))
                    await self._w.drain()
                    continue
                raise MysqlError(0, f"unknown AuthMoreData {more[:1].hex()}")
            raise MysqlError(0, f"unexpected auth packet {tag.hex()}")

    async def close(self) -> None:
        if self._w is not None:
            try:
                self._seq = 0
                self._write_packet(b"\x01")     # COM_QUIT
                await self._w.drain()
            except Exception:  # noqa: BLE001
                pass
            self._w.close()
            try:
                await self._w.wait_closed()
            except Exception:  # noqa: BLE001
                pass
            self._r = self._w = None

    async def ping(self) -> bool:
        self._seq = 0
        self._write_packet(b"\x0e")             # COM_PING
        await self._w.drain()
        return (await self._read_packet())[:1] == b"\x00"

    async def query(self, sql: str, params: Optional[list] = None
                    ) -> tuple[list[str], list[list]]:
        """Query -> (column_names, rows). Values are str or None for NULL;
        non-SELECT -> ([], []).

        Parameterized queries (`?` placeholders) go through server-side
        prepared statements (COM_STMT_PREPARE/EXECUTE) like the reference's
        mysql-otp — parameters never enter the SQL text, so no sql_mode
        (e.g. NO_BACKSLASH_ESCAPES) can turn them into injection.
        """
        if self._w is None:
            raise ConnectionError("mysql client not connected")
        if params:
            return await self._query_prepared(sql, params)
        self._seq = 0
        self._write_packet(b"\x03" + sql.encode())
        await self._w.drain()
        first = await self._read_packet()
        if first[:1] == b"\xff":
            raise self._err(first)
        if first[:1] == b"\x00":                # OK packet (no resultset)
            return [], []
        ncols, _ = _lenenc(first, 0)
        columns, _types = await self._read_columns(ncols)
        rows: list[list] = []
        while True:
            pkt = await self._read_packet()
            if pkt[:1] == b"\xfe" and len(pkt) < 9:
                break
            if pkt[:1] == b"\xff":
                raise self._err(pkt)
            pos = 0
            row: list = []
            for _ in range(ncols):
                n, pos = _lenenc(pkt, pos)
                if n is None:
                    row.append(None)
                else:
                    row.append(pkt[pos:pos + n].decode("utf-8", "replace"))
                    pos += n
            rows.append(row)
        return columns, rows

    async def _read_columns(self, ncols: int
                            ) -> tuple[list[str], list[int]]:
        """Read ncols column definitions + the trailing EOF; returns
        (names, type codes — needed to decode binary rows)."""
        columns: list[str] = []
        types: list[int] = []
        for _ in range(ncols):
            cdef = await self._read_packet()
            # column def 4.1: catalog, schema, table, org_table, name,
            # org_name, fixed(0x0c): charset(2) length(4) type(1) ...
            pos = 0
            vals = []
            for _f in range(6):
                n, pos = _lenenc(cdef, pos)
                vals.append(cdef[pos:pos + (n or 0)])
                pos += n or 0
            columns.append(vals[4].decode())
            pos += 1 + 2 + 4                    # filler, charset, length
            types.append(cdef[pos] if pos < len(cdef) else 0xFD)
        eof = await self._read_packet()
        if eof[:1] != b"\xfe":
            raise MysqlError(0, "expected EOF after column definitions")
        return columns, types

    # ---- server-side prepared statements (binary protocol) ----------
    async def _query_prepared(self, sql: str, params: list
                              ) -> tuple[list[str], list[list]]:
        self._seq = 0
        self._write_packet(b"\x16" + sql.encode())     # COM_STMT_PREPARE
        await self._w.drain()
        first = await self._read_packet()
        if first[:1] == b"\xff":
            raise self._err(first)
        stmt_id, n_cols, n_params = struct.unpack_from("<IHH", first, 1)
        # everything past a successful PREPARE runs under the CLOSE
        # guard: an error mid-flow must neither leak the server-side
        # statement nor leave unread definition packets that would
        # desynchronize the next query on this pooled connection
        try:
            if n_params:
                await self._read_columns(n_params)     # param definitions
            if n_cols:
                await self._read_columns(n_cols)       # result columns
            if n_params != len(params):
                raise ValueError(f"query expects {n_params} params, "
                                 f"got {len(params)}")

            # COM_STMT_EXECUTE: null bitmap + new-params flag + types +
            # values
            null_bits = bytearray((len(params) + 7) // 8)
            types = b""
            values = b""
            for i, v in enumerate(params):
                if v is None:
                    null_bits[i // 8] |= 1 << (i % 8)
                    types += struct.pack("<H", 0x06)   # MYSQL_TYPE_NULL
                    continue
                if isinstance(v, bool):
                    v = int(v)
                if isinstance(v, int):
                    types += struct.pack("<H", 0x08)   # LONGLONG (signed)
                    values += struct.pack("<q", v)
                elif isinstance(v, float):
                    types += struct.pack("<H", 0x05)   # DOUBLE
                    values += struct.pack("<d", v)
                else:
                    vb = v if isinstance(v, (bytes, bytearray)) \
                        else str(v).encode()
                    types += struct.pack("<H", 0xFD)   # VAR_STRING
                    values += _enc_lenenc(bytes(vb))
            body = (b"\x17" + struct.pack("<IBI", stmt_id, 0, 1)
                    + bytes(null_bits) + b"\x01" + types + values)
            self._seq = 0
            self._write_packet(body)
            await self._w.drain()

            first = await self._read_packet()
            if first[:1] == b"\xff":
                raise self._err(first)
            if first[:1] == b"\x00":               # OK: no resultset
                return [], []
            ncols, _ = _lenenc(first, 0)
            columns, col_types = await self._read_columns(ncols)
            rows: list[list] = []
            while True:
                pkt = await self._read_packet()
                if pkt[:1] == b"\xfe" and len(pkt) < 9:
                    break
                if pkt[:1] == b"\xff":
                    raise self._err(pkt)
                rows.append(_decode_binary_row(pkt, ncols, col_types))
            return columns, rows
        finally:
            self._seq = 0
            self._write_packet(b"\x19" + struct.pack("<I", stmt_id))
            await self._w.drain()                  # COM_STMT_CLOSE (no ack)
