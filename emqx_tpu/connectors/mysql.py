"""MySQL connector: client/server protocol v10 text path over asyncio.

Parity: apps/emqx_connector/src/emqx_connector_mysql.erl (mysql-otp).
Implements the handshake (mysql_native_password + caching_sha2 fast path
is out of scope), COM_QUERY text resultsets and COM_PING. Parameterized
queries take `?` placeholders substituted client-side with full escaping
(the mysql-otp prepared path is server-side; the observable behavior —
typed params in, rows out — is the same for the broker's SELECT-by-key
authn/authz queries).
"""

from __future__ import annotations

import asyncio
import hashlib
import struct
from typing import Any, Optional

CLIENT_LONG_PASSWORD = 0x1
CLIENT_PROTOCOL_41 = 0x200
CLIENT_SECURE_CONNECTION = 0x8000
CLIENT_PLUGIN_AUTH = 0x80000
CLIENT_CONNECT_WITH_DB = 0x8
CLIENT_TRANSACTIONS = 0x2000


class MysqlError(Exception):
    def __init__(self, code: int, msg: str):
        self.code = code
        super().__init__(f"mysql error {code}: {msg}")


def _native_scramble(password: bytes, nonce: bytes) -> bytes:
    """SHA1(pw) XOR SHA1(nonce + SHA1(SHA1(pw))) — mysql_native_password."""
    if not password:
        return b""
    h1 = hashlib.sha1(password).digest()
    h2 = hashlib.sha1(h1).digest()
    h3 = hashlib.sha1(nonce + h2).digest()
    return bytes(a ^ b for a, b in zip(h1, h3))


def _lenenc(data: bytes, pos: int) -> tuple[Optional[int], int]:
    first = data[pos]
    if first < 0xFB:
        return first, pos + 1
    if first == 0xFB:
        return None, pos + 1                       # NULL
    if first == 0xFC:
        return struct.unpack_from("<H", data, pos + 1)[0], pos + 3
    if first == 0xFD:
        return int.from_bytes(data[pos + 1:pos + 4], "little"), pos + 4
    return struct.unpack_from("<Q", data, pos + 1)[0], pos + 9


def escape(value: Any) -> str:
    """SQL-literal encoding of a parameter (client-side prepared stmt)."""
    if value is None:
        return "NULL"
    if isinstance(value, bool):
        return "1" if value else "0"
    if isinstance(value, (int, float)):
        return str(value)
    if isinstance(value, (bytes, bytearray)):
        return "x'" + bytes(value).hex() + "'"
    s = str(value)
    s = (s.replace("\\", "\\\\").replace("'", "\\'")
          .replace("\x00", "\\0").replace("\n", "\\n").replace("\r", "\\r")
          .replace("\x1a", "\\Z"))
    return f"'{s}'"


def bind_params(query: str, params: list) -> str:
    parts = query.split("?")
    if len(parts) - 1 != len(params):
        raise ValueError(f"query expects {len(parts)-1} params, "
                         f"got {len(params)}")
    out = [parts[0]]
    for val, tail in zip(params, parts[1:]):
        out.append(escape(val))
        out.append(tail)
    return "".join(out)


class MysqlClient:
    def __init__(self, host: str = "127.0.0.1", port: int = 3306,
                 username: str = "root", password: str = "",
                 database: Optional[str] = None, ssl=None,
                 connect_timeout: float = 5.0):
        self.host = host
        self.port = port
        self.username = username
        self.password = password
        self.database = database
        self.ssl = ssl
        self.connect_timeout = connect_timeout
        self._r: Optional[asyncio.StreamReader] = None
        self._w: Optional[asyncio.StreamWriter] = None
        self._seq = 0

    # ---- packet framing: 3-byte length + sequence id ----
    async def _read_packet(self) -> bytes:
        head = await self._r.readexactly(4)
        n = int.from_bytes(head[:3], "little")
        self._seq = (head[3] + 1) & 0xFF
        return await self._r.readexactly(n)

    def _write_packet(self, payload: bytes) -> None:
        self._w.write(len(payload).to_bytes(3, "little")
                      + bytes([self._seq]) + payload)
        self._seq = (self._seq + 1) & 0xFF

    @staticmethod
    def _err(payload: bytes) -> MysqlError:
        code = struct.unpack_from("<H", payload, 1)[0]
        msg = payload[3:].decode("utf-8", "replace")
        if msg.startswith("#"):       # SQL-state marker
            msg = msg[6:]
        return MysqlError(code, msg)

    async def connect(self) -> None:
        self._r, self._w = await asyncio.wait_for(
            asyncio.open_connection(self.host, self.port, ssl=self.ssl),
            self.connect_timeout)
        try:
            await self._handshake()
        except BaseException:
            self._w.close()     # auth failure must not leak the socket
            self._r = self._w = None
            raise

    async def _handshake(self) -> None:
        greet = await self._read_packet()
        if greet[:1] == b"\xff":
            raise self._err(greet)
        pos = 1
        end = greet.index(b"\x00", pos)         # server version string
        pos = end + 1 + 4                       # thread id
        nonce1 = greet[pos:pos + 8]
        pos += 8 + 1                            # filler
        pos += 2 + 1 + 2 + 2                    # caps-lo, charset, status,
        auth_len = greet[pos] if pos < len(greet) else 0   # caps-hi read ^
        pos += 1 + 10
        nonce2 = b""
        if auth_len:
            # part-2 is auth_len-8 bytes including a trailing NUL; the
            # scramble uses exactly 20 nonce bytes total
            nonce2 = greet[pos:pos + max(0, auth_len - 9)]
        nonce = (nonce1 + nonce2)[:20]

        caps = (CLIENT_LONG_PASSWORD | CLIENT_PROTOCOL_41 |
                CLIENT_SECURE_CONNECTION | CLIENT_PLUGIN_AUTH |
                CLIENT_TRANSACTIONS)
        if self.database:
            caps |= CLIENT_CONNECT_WITH_DB
        auth = _native_scramble(self.password.encode(), nonce)
        resp = struct.pack("<IIB23x", caps, 1 << 24, 0x21)  # utf8_general_ci
        resp += self.username.encode() + b"\x00"
        resp += bytes([len(auth)]) + auth
        if self.database:
            resp += self.database.encode() + b"\x00"
        resp += b"mysql_native_password\x00"
        self._write_packet(resp)

        reply = await self._read_packet()
        if reply[:1] == b"\xff":
            raise self._err(reply)
        if reply[:1] == b"\xfe":      # AuthSwitchRequest
            end = reply.index(b"\x00", 1)
            plugin = reply[1:end].decode()
            if plugin != "mysql_native_password":
                raise MysqlError(0, f"unsupported auth plugin {plugin}")
            new_nonce = reply[end + 1:]
            if new_nonce.endswith(b"\x00"):   # strip ONLY the terminator —
                new_nonce = new_nonce[:-1]    # scramble bytes may be 0x00
            self._write_packet(
                _native_scramble(self.password.encode(), new_nonce))
            reply = await self._read_packet()
            if reply[:1] == b"\xff":
                raise self._err(reply)

    async def close(self) -> None:
        if self._w is not None:
            try:
                self._seq = 0
                self._write_packet(b"\x01")     # COM_QUIT
                await self._w.drain()
            except Exception:  # noqa: BLE001
                pass
            self._w.close()
            try:
                await self._w.wait_closed()
            except Exception:  # noqa: BLE001
                pass
            self._r = self._w = None

    async def ping(self) -> bool:
        self._seq = 0
        self._write_packet(b"\x0e")             # COM_PING
        await self._w.drain()
        return (await self._read_packet())[:1] == b"\x00"

    async def query(self, sql: str, params: Optional[list] = None
                    ) -> tuple[list[str], list[list]]:
        """Text-protocol query -> (column_names, rows). Values are str
        (MySQL text protocol) or None for NULL; non-SELECT -> ([], [])."""
        if self._w is None:
            raise ConnectionError("mysql client not connected")
        if params:
            sql = bind_params(sql, params)
        self._seq = 0
        self._write_packet(b"\x03" + sql.encode())
        await self._w.drain()
        first = await self._read_packet()
        if first[:1] == b"\xff":
            raise self._err(first)
        if first[:1] == b"\x00":                # OK packet (no resultset)
            return [], []
        ncols, _ = _lenenc(first, 0)
        columns: list[str] = []
        for _ in range(ncols):
            cdef = await self._read_packet()
            # column def 4.1: catalog, schema, table, org_table, name, ...
            pos = 0
            vals = []
            for _f in range(5):
                n, pos = _lenenc(cdef, pos)
                vals.append(cdef[pos:pos + (n or 0)])
                pos += n or 0
            columns.append(vals[4].decode())
        eof = await self._read_packet()
        if eof[:1] != b"\xfe":
            raise MysqlError(0, "expected EOF after column definitions")
        rows: list[list] = []
        while True:
            pkt = await self._read_packet()
            if pkt[:1] == b"\xfe" and len(pkt) < 9:
                break
            if pkt[:1] == b"\xff":
                raise self._err(pkt)
            pos = 0
            row: list = []
            for _ in range(ncols):
                n, pos = _lenenc(pkt, pos)
                if n is None:
                    row.append(None)
                else:
                    row.append(pkt[pos:pos + n].decode("utf-8", "replace"))
                    pos += n
            rows.append(row)
        return columns, rows
