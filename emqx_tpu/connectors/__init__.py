"""Database connectors: asyncio wire-protocol clients.

Parity: apps/emqx_connector — the reference wraps Erlang driver libraries
(eredis/mysql-otp/epgsql/mongodb/eldap) in ecpool worker pools; no Python
drivers exist in this environment, so each connector speaks its database's
wire protocol directly over asyncio streams, pooled by `pool.ConnPool`.
"""

from emqx_tpu.connectors.pool import ConnPool                # noqa: F401
from emqx_tpu.connectors.redis import (RedisClient, RedisError,  # noqa: F401
                                       SentinelRedisClient)
from emqx_tpu.connectors.mysql import MysqlClient, MysqlError  # noqa: F401
from emqx_tpu.connectors.pgsql import PgsqlClient, PgsqlError  # noqa: F401
from emqx_tpu.connectors.mongo import MongoClient, MongoError  # noqa: F401
from emqx_tpu.connectors.ldap import LdapClient, LdapError     # noqa: F401
