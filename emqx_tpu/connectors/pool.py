"""Fixed-size asyncio connection pool (the ecpool analog).

Parity: the reference gives every connector an ecpool of N workers, each
holding one driver connection (apps/emqx_connector/src/*, `pool_size`
field in emqx_connector_schema_lib.erl). Here: N lazily-(re)connected
client objects behind an asyncio queue; `run()` borrows one, retries once
on a connection-level failure with a fresh connection, and drops the
connection (slot reconnects lazily) on any other failure since the
protocol state is then unknown.
"""

from __future__ import annotations

import asyncio
from typing import Awaitable, Callable, Optional

_IO_ERRORS = (ConnectionError, asyncio.IncompleteReadError, EOFError,
              OSError)


class ConnPool:
    def __init__(self, factory: Callable[[], object], size: int = 4):
        self._factory = factory
        self.size = size
        self._free: asyncio.Queue = asyncio.Queue()
        self._clients: list = []
        self._started = False

    async def start(self) -> None:
        """Open the first connection eagerly (health signal); the rest
        connect lazily on first use."""
        if self._started:
            return
        self._started = True
        first = self._factory()
        try:
            await first.connect()
        except BaseException:
            self._started = False
            raise
        self._clients.append(first)
        self._free.put_nowait(first)
        for _ in range(self.size - 1):
            self._free.put_nowait(None)     # lazy slot

    async def stop(self) -> None:
        self._started = False
        for c in self._clients:
            await _safe_close(c)
        self._clients.clear()
        while not self._free.empty():
            self._free.get_nowait()

    async def _acquire(self):
        if not self._started:
            raise ConnectionError("pool not started")
        client = await self._free.get()
        if client is None:
            client = self._factory()
            try:
                await client.connect()
            except BaseException:
                # ANY connect failure (auth rejection included) must give
                # the slot token back or the pool shrinks to a deadlock
                self._free.put_nowait(None)
                raise
            self._clients.append(client)
        return client

    def _drop(self, client) -> None:
        if client in self._clients:
            self._clients.remove(client)
        if self._started:
            self._free.put_nowait(None)

    async def run(self, op: Callable[[object], Awaitable],
                  timeout: Optional[float] = None):
        """Run op(client) on a pooled connection."""
        client = await self._acquire()   # restores its slot on failure
        try:
            result = await asyncio.wait_for(op(client), timeout)
        except _IO_ERRORS:
            await _safe_close(client)
            try:
                await client.connect()
                result = await asyncio.wait_for(op(client), timeout)
            except BaseException:
                await _safe_close(client)
                self._drop(client)
                raise
            if self._started:
                self._free.put_nowait(client)
            return result
        except BaseException:
            await _safe_close(client)
            self._drop(client)
            raise
        else:
            if self._started:
                self._free.put_nowait(client)
            return result


async def _safe_close(client) -> None:
    try:
        await client.close()
    except Exception:  # noqa: BLE001
        pass
