"""MongoDB connector: OP_MSG wire protocol over asyncio.

Parity: apps/emqx_connector/src/emqx_connector_mongo.erl (mongodb driver,
single/rs/sharded topologies). Single-server mode: every database command
(ping, find, insert, saslStart/saslContinue) is one OP_MSG (opcode 2013)
round-trip carrying a kind-0 BSON section; auth is SCRAM-SHA-256 (or
SHA-1) over saslStart/saslContinue like the reference driver.
"""

from __future__ import annotations

import asyncio
import base64
import itertools
import struct
from typing import Optional

from emqx_tpu.utils import bson
from emqx_tpu.utils.scram import ScramClient

OP_MSG = 2013


class MongoError(Exception):
    def __init__(self, doc: dict):
        self.doc = doc
        super().__init__(doc.get("errmsg", "mongodb error")
                         + f" (code {doc.get('code', '?')})")


class MongoClient:
    _req_ids = itertools.count(1)

    def __init__(self, host: str = "127.0.0.1", port: int = 27017,
                 username: Optional[str] = None, password: str = "",
                 database: str = "mqtt", auth_source: str = "admin",
                 auth_algo: str = "sha256", ssl=None,
                 connect_timeout: float = 5.0):
        self.host = host
        self.port = port
        self.username = username
        self.password = password
        self.database = database
        self.auth_source = auth_source
        self.auth_algo = auth_algo
        self.ssl = ssl
        self.connect_timeout = connect_timeout
        self._r: Optional[asyncio.StreamReader] = None
        self._w: Optional[asyncio.StreamWriter] = None

    async def connect(self) -> None:
        self._r, self._w = await asyncio.wait_for(
            asyncio.open_connection(self.host, self.port, ssl=self.ssl),
            self.connect_timeout)
        if self.username:
            try:
                await self._sasl_auth()
            except BaseException:
                self._w.close()  # auth failure must not leak the socket
                self._r = self._w = None
                raise

    async def _sasl_auth(self) -> None:
        mech = ("SCRAM-SHA-256" if self.auth_algo == "sha256"
                else "SCRAM-SHA-1")
        scram = ScramClient(self.username, self.password, self.auth_algo)
        first = await self.command({
            "saslStart": 1, "mechanism": mech,
            "payload": scram.first().encode(),
            "options": {"skipEmptyExchange": True}}, db=self.auth_source)
        final = scram.final(bytes(first["payload"]).decode())
        done = await self.command({
            "saslContinue": 1,
            "conversationId": first.get("conversationId", 1),
            "payload": final.encode()}, db=self.auth_source)
        if not scram.verify_server(bytes(done["payload"]).decode()):
            raise MongoError({"errmsg": "server SCRAM signature invalid"})
        while not done.get("done", True):
            done = await self.command({
                "saslContinue": 1,
                "conversationId": first.get("conversationId", 1),
                "payload": b""}, db=self.auth_source)

    async def close(self) -> None:
        if self._w is not None:
            self._w.close()
            try:
                await self._w.wait_closed()
            except Exception:  # noqa: BLE001
                pass
            self._r = self._w = None

    async def ping(self) -> bool:
        await self.command({"ping": 1})     # raises MongoError on ok:0
        return True

    async def command(self, doc: dict, db: Optional[str] = None) -> dict:
        """One OP_MSG command -> response doc; raises MongoError on ok:0."""
        if self._w is None:
            raise ConnectionError("mongo client not connected")
        body = dict(doc)
        body["$db"] = db or self.database
        payload = struct.pack("<i", 0) + b"\x00" + bson.encode(body)
        req_id = next(self._req_ids)
        header = struct.pack("<iiii", len(payload) + 16, req_id, 0, OP_MSG)
        self._w.write(header + payload)
        await self._w.drain()
        head = await self._r.readexactly(16)
        total, _rid, _resp_to, opcode = struct.unpack("<iiii", head)
        data = await self._r.readexactly(total - 16)
        if opcode != OP_MSG:
            raise MongoError({"errmsg": f"unexpected opcode {opcode}"})
        # flags(4) + section kind(1) + BSON doc
        reply = bson.decode(data[5:])
        if reply.get("ok") != 1 and reply.get("ok") != 1.0:
            raise MongoError(reply)
        return reply

    # ---- convenience surface used by authn/authz/rule actions ----
    async def find(self, collection: str, filter_doc: dict,
                   limit: int = 0) -> list[dict]:
        cmd = {"find": collection, "filter": filter_doc}
        if limit:
            cmd["limit"] = limit
        reply = await self.command(cmd)
        cursor = reply.get("cursor", {})
        out = list(cursor.get("firstBatch", []))
        # drain the cursor: firstBatch caps at the server default (~101
        # docs); results past it need getMore until cursor id 0
        cid = cursor.get("id", 0)
        while cid:
            reply = await self.command({"getMore": cid,
                                        "collection": collection})
            cursor = reply.get("cursor", {})
            out.extend(cursor.get("nextBatch", []))
            cid = cursor.get("id", 0)
            if limit and len(out) >= limit:
                if cid:
                    try:
                        await self.command({"killCursors": collection,
                                            "cursors": [cid]})
                    except MongoError:
                        pass
                return out[:limit]
        return out

    async def find_one(self, collection: str,
                       filter_doc: dict) -> Optional[dict]:
        rows = await self.find(collection, filter_doc, limit=1)
        return rows[0] if rows else None

    async def insert(self, collection: str, docs: list[dict]) -> int:
        reply = await self.command({"insert": collection,
                                    "documents": docs})
        return int(reply.get("n", 0))
