"""PostgreSQL connector: frontend/backend protocol v3 over asyncio.

Parity: apps/emqx_connector/src/emqx_connector_pgsql.erl (epgsql).
Implements startup, auth (trust / cleartext / md5 / SCRAM-SHA-256 SASL),
and the simple-query cycle. Parameterized queries use `$1..$n`
placeholders substituted client-side with literal escaping — same
observable behavior as epgsql's equery for the broker's SELECT-by-key
authn/authz queries.
"""

from __future__ import annotations

import asyncio
import hashlib
import re
import struct
from typing import Any, Optional

from emqx_tpu.utils.scram import ScramClient


class PgsqlError(Exception):
    def __init__(self, fields: dict):
        self.fields = fields
        super().__init__(fields.get("M", "postgres error")
                         + f" (code {fields.get('C', '?')})")


def escape(value: Any) -> str:
    if value is None:
        return "NULL"
    if isinstance(value, bool):
        return "TRUE" if value else "FALSE"
    if isinstance(value, (int, float)):
        return str(value)
    if isinstance(value, (bytes, bytearray)):
        return "'\\x" + bytes(value).hex() + "'"
    s = str(value).replace("'", "''")
    if "\\" in s:
        return "E'" + s.replace("\\", "\\\\") + "'"
    return f"'{s}'"


_PARAM_RE = re.compile(r"\$(\d+)")


def bind_params(query: str, params: list) -> str:
    # single-pass substitution: a parameter VALUE containing "$1" must
    # never be re-substituted (injection via client-controlled strings)
    def _sub(m: re.Match) -> str:
        idx = int(m.group(1))
        if not 1 <= idx <= len(params):
            raise ValueError(f"query references ${idx} but only "
                             f"{len(params)} params given")
        return escape(params[idx - 1])

    return _PARAM_RE.sub(_sub, query)


class PgsqlClient:
    def __init__(self, host: str = "127.0.0.1", port: int = 5432,
                 username: str = "postgres", password: str = "",
                 database: str = "postgres", ssl=None,
                 connect_timeout: float = 5.0):
        self.host = host
        self.port = port
        self.username = username
        self.password = password
        self.database = database
        self.ssl = ssl
        self.connect_timeout = connect_timeout
        self.parameters: dict[str, str] = {}
        self._r: Optional[asyncio.StreamReader] = None
        self._w: Optional[asyncio.StreamWriter] = None

    # ---- message framing: type byte + int32 length (incl. itself) ----
    async def _read_msg(self) -> tuple[bytes, bytes]:
        head = await self._r.readexactly(5)
        mtype = head[:1]
        n = struct.unpack(">i", head[1:])[0]
        return mtype, await self._r.readexactly(n - 4)

    def _write_msg(self, mtype: bytes, payload: bytes) -> None:
        self._w.write(mtype + struct.pack(">i", len(payload) + 4) + payload)

    @staticmethod
    def _err_fields(body: bytes) -> dict:
        fields: dict[str, str] = {}
        pos = 0
        while pos < len(body) and body[pos] != 0:
            code = chr(body[pos])
            end = body.index(b"\x00", pos + 1)
            fields[code] = body[pos + 1:end].decode("utf-8", "replace")
            pos = end + 1
        return fields

    async def connect(self) -> None:
        self._r, self._w = await asyncio.wait_for(
            asyncio.open_connection(self.host, self.port, ssl=self.ssl),
            self.connect_timeout)
        params = (b"user\x00" + self.username.encode() + b"\x00"
                  b"database\x00" + self.database.encode() + b"\x00\x00")
        payload = struct.pack(">i", 196608) + params      # protocol 3.0
        self._w.write(struct.pack(">i", len(payload) + 4) + payload)
        await self._w.drain()
        scram: Optional[ScramClient] = None
        while True:
            mtype, body = await self._read_msg()
            if mtype == b"E":
                raise PgsqlError(self._err_fields(body))
            if mtype == b"R":
                kind = struct.unpack(">i", body[:4])[0]
                if kind == 0:                              # AuthenticationOk
                    continue
                if kind == 3:                              # cleartext
                    self._write_msg(b"p", self.password.encode() + b"\x00")
                elif kind == 5:                            # md5
                    salt = body[4:8]
                    inner = hashlib.md5(
                        self.password.encode()
                        + self.username.encode()).hexdigest()
                    outer = hashlib.md5(
                        inner.encode() + salt).hexdigest()
                    self._write_msg(b"p", b"md5" + outer.encode() + b"\x00")
                elif kind == 10:                           # SASL mechanisms
                    mechs = body[4:].split(b"\x00")
                    if b"SCRAM-SHA-256" not in mechs:
                        raise PgsqlError(
                            {"M": "no supported SASL mechanism"})
                    scram = ScramClient(self.username, self.password,
                                        "sha256")
                    first = scram.first().encode()
                    self._write_msg(
                        b"p", b"SCRAM-SHA-256\x00"
                        + struct.pack(">i", len(first)) + first)
                elif kind == 11:                           # SASL continue
                    final = scram.final(body[4:].decode()).encode()
                    self._write_msg(b"p", final)
                elif kind == 12:                           # SASL final
                    if not scram.verify_server(body[4:].decode()):
                        raise PgsqlError(
                            {"M": "server SCRAM signature invalid"})
                else:
                    raise PgsqlError(
                        {"M": f"unsupported auth request {kind}"})
                await self._w.drain()
            elif mtype == b"S":
                k, v = body.split(b"\x00")[:2]
                self.parameters[k.decode()] = v.decode("utf-8", "replace")
            elif mtype == b"K":                            # BackendKeyData
                continue
            elif mtype == b"Z":                            # ReadyForQuery
                return
            # NoticeResponse ('N') and anything else: skip

    async def close(self) -> None:
        if self._w is not None:
            try:
                self._write_msg(b"X", b"")                 # Terminate
                await self._w.drain()
            except Exception:  # noqa: BLE001
                pass
            self._w.close()
            try:
                await self._w.wait_closed()
            except Exception:  # noqa: BLE001
                pass
            self._r = self._w = None

    async def ping(self) -> bool:
        cols, rows = await self.query("SELECT 1")
        return bool(rows)

    async def query(self, sql: str, params: Optional[list] = None
                    ) -> tuple[list[str], list[list]]:
        """Simple-query cycle -> (column_names, rows); text values."""
        if self._w is None:
            raise ConnectionError("pgsql client not connected")
        if params:
            sql = bind_params(sql, params)
        self._write_msg(b"Q", sql.encode() + b"\x00")
        await self._w.drain()
        columns: list[str] = []
        rows: list[list] = []
        error: Optional[PgsqlError] = None
        while True:
            mtype, body = await self._read_msg()
            if mtype == b"T":                              # RowDescription
                nf = struct.unpack(">h", body[:2])[0]
                pos = 2
                columns = []
                for _ in range(nf):
                    end = body.index(b"\x00", pos)
                    columns.append(body[pos:end].decode())
                    pos = end + 1 + 18       # table oid..format code
            elif mtype == b"D":                            # DataRow
                nf = struct.unpack(">h", body[:2])[0]
                pos = 2
                row: list = []
                for _ in range(nf):
                    n = struct.unpack_from(">i", body, pos)[0]
                    pos += 4
                    if n == -1:
                        row.append(None)
                    else:
                        row.append(body[pos:pos + n]
                                   .decode("utf-8", "replace"))
                        pos += n
                rows.append(row)
            elif mtype == b"E":
                error = PgsqlError(self._err_fields(body))
            elif mtype == b"Z":                            # ReadyForQuery
                if error is not None:
                    raise error
                return columns, rows
            # CommandComplete ('C'), EmptyQueryResponse ('I'), notices: skip
