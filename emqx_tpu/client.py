"""Asyncio MQTT client (v3.1.1 / v5).

Role: the reference bundles the `emqtt` client for conformance suites and
the MQTT data bridge (emqx_bridge_worker.erl); this is the equivalent —
a small, complete client over the same wire codec, used by tests and by
the egress MQTT bridge.
"""

from __future__ import annotations

import asyncio
from typing import Optional

from emqx_tpu.mqtt import constants as C
from emqx_tpu.mqtt import packet as P
from emqx_tpu.mqtt.frame import FrameParser, serialize


class MqttError(Exception):
    pass


class Client:
    def __init__(self, host: str = "127.0.0.1", port: int = 1883, *,
                 clientid: str = "", username: Optional[str] = None,
                 password: Optional[bytes] = None, clean_start: bool = True,
                 keepalive: int = 0, proto_ver: int = C.MQTT_V4,
                 properties: Optional[dict] = None,
                 will: Optional[P.Will] = None, ssl=None,
                 conn_factory=None):
        # conn_factory: async () -> (reader, writer) for non-TCP
        # transports (the QUIC stream pair; the reference's emqtt takes a
        # quic option the same way)
        self._conn_factory = conn_factory
        self.host, self.port = host, port
        # ssl: an ssl.SSLContext, or a dict of emqx-style client tls opts
        if isinstance(ssl, dict):
            from emqx_tpu.utils.tls import make_client_context
            ssl = make_client_context(ssl)
        self.ssl = ssl
        self.clientid = clientid
        self.username, self.password = username, password
        self.clean_start = clean_start
        self.keepalive = keepalive
        self.proto_ver = proto_ver
        self.conn_props = properties
        self.will = will

        self.messages: asyncio.Queue[P.Publish] = asyncio.Queue()
        self.connack: Optional[P.Connack] = None
        self.disconnect_pkt: Optional[P.Disconnect] = None
        self._reader = None
        self._writer = None
        self._parser = FrameParser(version=proto_ver)
        self._rx_task: Optional[asyncio.Task] = None
        self._next_pid = 0
        self._acks: dict[int, asyncio.Future] = {}
        self._suback: dict[int, asyncio.Future] = {}
        self.closed = asyncio.Event()
        self.auto_ack = True
        # qos-0 pipelining backpressure (see publish_start): flood loops
        # must `await drain()` at least every `qos0_drain_every`
        # publish_start(qos=0) calls or the transport buffer grows
        # unboundedly (asyncio never blocks a bare write())
        self.qos0_drain_every = 64
        self._q0_undrained = 0
        self._scram = None
        self._scram_mech = ""
        self.scram_server_ok: Optional[bool] = None
        self._reauth_fut: Optional[asyncio.Future] = None

    def enable_scram(self, username: str, password: str,
                     algorithm: str = "sha256") -> None:
        """MQTT5 enhanced authentication: carry SCRAM client-first in
        CONNECT and answer the broker's AUTH challenge."""
        from emqx_tpu.utils.scram import ScramClient
        self._scram = ScramClient(username, password, algorithm)
        self._scram_mech = "SCRAM-SHA-" + \
            ("1" if algorithm == "sha1" else algorithm[3:])
        self.conn_props = dict(self.conn_props or {})
        self.conn_props["authentication_method"] = self._scram_mech
        self.conn_props["authentication_data"] = self._scram.first().encode()

    async def reauthenticate(self, username: str, password: str,
                             algorithm: str = "sha256",
                             timeout: float = 5.0) -> bool:
        """AUTH rc=0x19 re-authentication exchange; True on success."""
        from emqx_tpu.utils.scram import ScramClient
        self._scram = ScramClient(username, password, algorithm)
        self._scram_mech = "SCRAM-SHA-" + \
            ("1" if algorithm == "sha1" else algorithm[3:])
        self._reauth_fut = asyncio.get_event_loop().create_future()
        self._send(P.Auth(
            reason_code=C.RC_RE_AUTHENTICATE,
            properties={"authentication_method": self._scram_mech,
                        "authentication_data":
                            self._scram.first().encode()}))
        return await asyncio.wait_for(self._reauth_fut, timeout)

    def _alloc(self) -> int:
        self._next_pid = (self._next_pid % C.MAX_PACKET_ID) + 1
        return self._next_pid

    async def connect(self, timeout: float = 5.0) -> P.Connack:
        if self._conn_factory is not None:
            self._reader, self._writer = await self._conn_factory()
        else:
            self._reader, self._writer = await asyncio.open_connection(
                self.host, self.port, ssl=self.ssl)
        pkt = P.Connect(
            proto_name=C.PROTOCOL_NAMES[self.proto_ver],
            proto_ver=self.proto_ver, clean_start=self.clean_start,
            keepalive=self.keepalive, clientid=self.clientid,
            username=self.username, password=self.password,
            will=self.will, properties=self.conn_props)
        self._send(pkt)
        self._rx_task = asyncio.ensure_future(self._rx_loop())
        fut = asyncio.get_event_loop().create_future()
        self._connack_fut = fut
        self.connack = await asyncio.wait_for(fut, timeout)
        if self.connack.reason_code != 0:
            raise MqttError(f"connack rc={self.connack.reason_code}")
        return self.connack

    def _send(self, pkt: P.Packet) -> None:
        if self._writer is None or self._writer.is_closing():
            raise MqttError("not connected")
        self._writer.write(serialize(pkt, self.proto_ver))

    async def _rx_loop(self) -> None:
        try:
            while True:
                data = await self._reader.read(65536)
                if not data:
                    break
                for i, pkt in enumerate(self._parser.feed(data)):
                    self._handle(pkt)
                    if i % 64 == 63:
                        # a 64KB read can carry hundreds of deliveries;
                        # yield so co-located tasks (broker in-process
                        # tests/benches) are not starved for the burst
                        await asyncio.sleep(0)
        except (asyncio.CancelledError, ConnectionResetError):
            pass
        finally:
            self.closed.set()
            for fut in list(self._acks.values()) + list(self._suback.values()):
                if not fut.done():
                    fut.set_exception(MqttError("connection closed"))
            if getattr(self, "_connack_fut", None) and \
                    not self._connack_fut.done():
                self._connack_fut.set_exception(MqttError("closed"))

    def _handle(self, pkt: P.Packet) -> None:
        if isinstance(pkt, P.Connack):
            if self._scram is not None and pkt.reason_code == 0:
                data = (pkt.properties or {}).get("authentication_data")
                self.scram_server_ok = bool(data) and \
                    self._scram.verify_server(bytes(data).decode())
            if not self._connack_fut.done():
                self._connack_fut.set_result(pkt)
        elif isinstance(pkt, P.Auth):
            props = pkt.properties or {}
            if pkt.reason_code == C.RC_CONTINUE_AUTHENTICATION and \
                    self._scram is not None:
                data = bytes(props.get("authentication_data", b""))
                final = self._scram.final(data.decode())
                self._send(P.Auth(
                    reason_code=C.RC_CONTINUE_AUTHENTICATION,
                    properties={"authentication_method": self._scram_mech,
                                "authentication_data": final.encode()}))
            elif pkt.reason_code == 0 and self._reauth_fut is not None:
                data = props.get("authentication_data")
                ok = bool(data) and \
                    self._scram.verify_server(bytes(data).decode())
                if not self._reauth_fut.done():
                    self._reauth_fut.set_result(ok)
                self._reauth_fut = None
        elif isinstance(pkt, P.Publish):
            if pkt.qos == 1 and self.auto_ack:
                self._send(P.Puback(packet_id=pkt.packet_id))
            elif pkt.qos == 2 and self.auto_ack:
                self._send(P.Pubrec(packet_id=pkt.packet_id))
            self.messages.put_nowait(pkt)
        elif isinstance(pkt, (P.Puback, P.Pubcomp)):
            fut = self._acks.pop(pkt.packet_id, None)
            if fut and not fut.done():
                fut.set_result(pkt)
        elif isinstance(pkt, P.Pubrec):
            self._send(P.Pubrel(packet_id=pkt.packet_id))
        elif isinstance(pkt, P.Pubrel):
            if self.auto_ack:
                self._send(P.Pubcomp(packet_id=pkt.packet_id))
        elif isinstance(pkt, (P.Suback, P.Unsuback)):
            fut = self._suback.pop(pkt.packet_id, None)
            if fut and not fut.done():
                fut.set_result(pkt)
        elif isinstance(pkt, P.Pingresp):
            pass
        elif isinstance(pkt, P.Disconnect):
            self.disconnect_pkt = pkt

    async def subscribe(self, topic_filter, qos: int = 0,
                        opts: Optional[dict] = None,
                        properties: Optional[dict] = None,
                        timeout: float = 5.0) -> P.Suback:
        if isinstance(topic_filter, list):
            filters = topic_filter
        else:
            o = dict(opts or {})
            filters = [(topic_filter, P.SubOpts(
                qos=qos, nl=o.get("nl", 0), rap=o.get("rap", 0),
                rh=o.get("rh", 0)))]
        pid = self._alloc()
        fut = asyncio.get_event_loop().create_future()
        self._suback[pid] = fut
        self._send(P.Subscribe(packet_id=pid, filters=filters,
                               properties=properties or {}))
        return await asyncio.wait_for(fut, timeout)

    async def unsubscribe(self, topic_filter,
                          timeout: float = 5.0) -> P.Unsuback:
        filters = topic_filter if isinstance(topic_filter, list) \
            else [topic_filter]
        pid = self._alloc()
        fut = asyncio.get_event_loop().create_future()
        self._suback[pid] = fut
        self._send(P.Unsubscribe(packet_id=pid, filters=filters))
        return await asyncio.wait_for(fut, timeout)

    def publish_start(self, topic: str, payload: bytes = b"", qos: int = 0,
                      retain: bool = False,
                      properties: Optional[dict] = None):
        """Send a PUBLISH without awaiting its ack: for qos>0 returns the
        ack future (await it later — pipelined publishing keeps a flood's
        connections full instead of stalling a round trip per message).

        PIPELINE CONTRACT (qos 0): the return is None and the bytes only
        sit in the transport write buffer — asyncio's write() never
        blocks, so a tight flood loop grows that buffer without bound.
        Callers pipelining qos-0 publishes MUST apply backpressure by
        awaiting `drain()` periodically; `needs_drain` flips True every
        `qos0_drain_every` un-drained qos-0 publishes as the cue:

            cl.publish_start(t, p)            # fire-and-forget
            if cl.needs_drain:
                await cl.drain()              # bounded transport buffer

        qos>0 floods get the same bound for free by awaiting their ack
        futures in windows (the broker acks only what it has read)."""
        if qos == 0:
            self._send(P.Publish(topic=topic, payload=payload, qos=0,
                                 retain=retain, properties=properties))
            self._q0_undrained += 1
            return None
        pid = self._alloc()
        fut = asyncio.get_event_loop().create_future()
        self._acks[pid] = fut
        self._send(P.Publish(topic=topic, payload=payload, qos=qos,
                             retain=retain, packet_id=pid,
                             properties=properties))
        return fut

    @property
    def needs_drain(self) -> bool:
        """True once `qos0_drain_every` qos-0 publishes went un-drained —
        the publish_start pipeline contract's backpressure cue."""
        return self._q0_undrained >= self.qos0_drain_every

    async def drain(self) -> None:
        """Flush the transport write buffer (asyncio flow control): the
        qos-0 pipeline contract's backpressure point. Stalls only while
        the buffer is over the transport's high-water mark."""
        self._q0_undrained = 0
        if self._writer is not None:
            await self._writer.drain()

    async def publish(self, topic: str, payload: bytes = b"", qos: int = 0,
                      retain: bool = False,
                      properties: Optional[dict] = None,
                      timeout: float = 5.0) -> Optional[P.Packet]:
        fut = self.publish_start(topic, payload, qos, retain, properties)
        if fut is None:
            await self.drain()
            return None
        return await asyncio.wait_for(fut, timeout)

    async def recv(self, timeout: float = 5.0) -> P.Publish:
        return await asyncio.wait_for(self.messages.get(), timeout)

    async def ping(self) -> None:
        self._send(P.Pingreq())

    async def disconnect(self, reason_code: int = 0,
                         properties: Optional[dict] = None) -> None:
        try:
            self._send(P.Disconnect(reason_code=reason_code,
                                    properties=properties))
            await self._writer.drain()
        except (MqttError, ConnectionResetError):
            pass
        await self.close()

    async def close(self) -> None:
        if self._rx_task:
            self._rx_task.cancel()
        if self._writer and not self._writer.is_closing():
            self._writer.close()
            try:
                await self._writer.wait_closed()
            except Exception:  # noqa: BLE001 — best-effort close: the
                pass           # peer may already have reset the socket
        self.closed.set()
