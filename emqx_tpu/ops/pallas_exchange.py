"""Device-to-device ring rotation for the sharded exchange stage.

The mesh route path (parallel/serving.py) matches per-shard on device
but, until ISSUE 15, funneled every shard's results through host-side
gather/merge — PR 9's stage decomposition showed that funnel is the
wall at the SHARDED_r05 shape. The exchange stage re-keys each shard's
matched delivery rows by their OWNING delivery shard (session-affine,
the same ``sid % n`` discipline as the PR 5 lanes) and moves the CSR
segments device-to-device around the 'route' ring, so each host lands
only its own shard's final delivery plan.

This module provides the one collective the exchange program needs —
"rotate this block k positions around the ring" — in two twin
implementations selected by backend:

* ``pallas``: a `pltpu.make_async_remote_copy` kernel (SNIPPETS.md [2],
  the worked right-permute example; /opt guide "Async Remote DMA"):
  one RDMA per device per round, straight over the interconnect with
  send/recv DMA semaphores. TPU only — Mosaic lowers it; exercised by
  the slow-marked hardware smoke test.
* ``ppermute``: `jax.lax.ppermute` with the rotation permutation — the
  portable path XLA lowers to its collective-permute on every backend,
  bit-identical to the kernel by construction (both are pure data
  movement). This is what the XLA-CPU tier-1 suite and the 8-device
  virtual-mesh oracle tests run.

Selection is one function (`exchange_rotate_impl`) so the tier-1 gate
can assert the twin wiring without touching Mosaic on CPU.
"""

from __future__ import annotations

import jax

__all__ = ["exchange_rotate_impl", "ring_rotate"]


def exchange_rotate_impl(backend: "str | None" = None) -> str:
    """Which rotate twin serves this process: 'pallas' on real TPU,
    'ppermute' everywhere else (including TPU-interpret test runs —
    interpret-mode remote DMA is not supported, and the ppermute twin
    is the portable oracle anyway)."""
    backend = backend or jax.default_backend()
    return "pallas" if backend == "tpu" else "ppermute"


def ring_rotate(block, k: int, axis_name: str, size: int, *,
                impl: "str | None" = None, lead_axes: tuple = ()):
    """Rotate `block` k hops around the `axis_name` ring.

    Inside a shard_map: every participant contributes its `block` and
    receives the block held by the participant k positions to its LEFT
    ((my - k) % size) — i.e. each device SENDS to (my + k) % size.
    `lead_axes` names the mesh axes ahead of `axis_name` (the 'dp'
    rows); the Pallas twin needs them to address the full logical mesh
    coordinate of the target chip.
    """
    if impl is None:
        impl = exchange_rotate_impl()
    if impl == "pallas":
        return _rotate_pallas(block, k, axis_name, size,
                              lead_axes=lead_axes)
    return jax.lax.ppermute(
        block, axis_name, [(j, (j + k) % size) for j in range(size)])


def _rotate_pallas(block, k: int, axis_name: str, size: int, *,
                   lead_axes: tuple = ()):
    """The remote-DMA twin (TPU only; lazily imports pallas so the CPU
    tier-1 path never touches Mosaic). One kernel invocation per round:
    copy the whole local block into the output buffer of the device
    k positions right around the `axis_name` ring, semaphore-synced."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    # jax 0.4 names these TPUMemorySpace.ANY / TPUCompilerParams; newer
    # releases flattened them — resolve once, tolerate both
    mem_any = getattr(pltpu, "ANY", None)
    if mem_any is None:
        mem_any = pltpu.TPUMemorySpace.ANY
    params_cls = getattr(pltpu, "CompilerParams", None)
    if params_cls is None:
        params_cls = pltpu.TPUCompilerParams

    def _kernel(x_ref, o_ref, send_sem, recv_sem):
        my = jax.lax.axis_index(axis_name)
        dst = jax.lax.rem(my + k, size)
        device_id = tuple(jax.lax.axis_index(a) for a in lead_axes) \
            + (dst,)
        copy = pltpu.make_async_remote_copy(
            src_ref=x_ref, dst_ref=o_ref,
            send_sem=send_sem, recv_sem=recv_sem,
            device_id=device_id,
            device_id_type=pltpu.DeviceIdType.LOGICAL)
        copy.start()
        copy.wait()

    call = pl.pallas_call(
        _kernel,
        out_shape=jax.ShapeDtypeStruct(block.shape, block.dtype),
        in_specs=[pl.BlockSpec(memory_space=mem_any)],
        out_specs=pl.BlockSpec(memory_space=mem_any),
        scratch_shapes=[pltpu.SemaphoreType.DMA] * 2,
        compiler_params=params_cls(collective_id=0),
    )
    return call(block)
