"""Pallas TPU kernel for the shape-hash fold (the matcher's VPU core).

The shape-directed matcher (ops/shapes.py, replacing the reference's
per-message trie walk, emqx_trie.erl:208-266) spends its compute in a
per-level hash fold over [batch, shapes] lanes followed by two-choice home
bucket derivation and shape-compatibility masking. This kernel fuses the
whole L-level fold, the home computation, and the compatibility mask into
ONE VMEM-resident Pallas program (grid over batch blocks), so the level
loop never materializes intermediates in HBM and the mask/index outputs
come out in a single pass. The two bucket-row gathers stay in XLA (Mosaic
has no large-table vector gather; the gather is HBM-bound either way).

Layout (round-3 rework): the round-2 kernel tiled blocks as
[batch, shapes] — with the bench's single shape that is a 1-wide LANE
dimension, which Mosaic pads to 128 lanes, i.e. 127/128 of every VPU op
wasted (measured: pallas 8.4M/s vs XLA 9.3M/s, the round-2 rent problem).
Here the batch block spans the full native tile — [SB=8 sublanes,
BL=512 lanes] — and the (static, <= 32) shape axis is an unrolled python
loop reading its per-shape metadata as SMEM scalars. Every elementwise op
runs on a dense [8, 512] tile regardless of how many shapes exist.

Bit-exactness: identical uint32 arithmetic to the jnp path — the oracle
tests assert match equality against ops.shapes.shape_match's fold, so
either backend can serve the same tables.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from emqx_tpu.ops.shapes import _fold, _homes

_U = np.uint32

SB = 8          # sublanes per batch block
BL_MAX = 512    # max lanes per batch block (block routes SB*BL topics)


def _seed_scalar(s: int, c1: int, c2: int) -> np.uint32:
    """_seed for a static shape id (same uint32 wraparound as ops.shapes,
    via masked python ints — numpy warns on scalar uint32 overflow)."""
    h = (s * c1 + c2) & 0xFFFFFFFF
    h ^= h >> 16
    h = (h * 0x7FEB352D) & 0xFFFFFFFF
    return _U(h ^ (h >> 13))


def _fold_kernel(L: int, NB: int, NSc: int, BL: int,
                 spm_ref, slen_ref, shh_ref, swr_ref,
                 topics_ref, lens_ref, dollar_ref,
                 h1_ref, h2_ref, b1_ref, b2_ref, compat_ref):
    lens_ = lens_ref[0]                       # [SB, BL]
    dollar = dollar_ref[0]
    for s in range(NSc):                      # static unroll over shapes
        slen = slen_ref[s]                    # SMEM scalars
        pmask = spm_ref[s]
        h1 = jnp.full((SB, BL), _seed_scalar(s, 0x27D4EB2F, 0x165667B1))
        h2 = jnp.full((SB, BL), _seed_scalar(s, 0x85EBCA6B, 0xC2B2AE3D))
        for l in range(L):
            concrete = (l < slen) & ((pmask >> l) & 1 == 0)   # scalar bool
            w = topics_ref[l, 0].astype(jnp.uint32)           # [SB, BL]
            h1 = jnp.where(concrete, _fold(h1, w, 2 * l), h1)
            h2 = jnp.where(concrete, _fold(h2, w, 2 * l + 1), h2)
        # int32 arithmetic throughout: Mosaic cannot truncate i8->i1, so
        # boolean select/and chains must stay integer-typed in-kernel
        len_ok = jnp.where(shh_ref[s] == 1,
                           (lens_ >= slen).astype(jnp.int32),
                           (lens_ == slen).astype(jnp.int32))
        real_shape = (slen >= 0).astype(jnp.int32)
        dollar_block = ((dollar != 0)
                        & (swr_ref[s] == 1)).astype(jnp.int32)
        nonempty = (lens_ > 0).astype(jnp.int32)
        compat = len_ok * real_shape * (1 - dollar_block) * nonempty
        b1, b2 = _homes(h1, h2, NB)
        h1_ref[s, 0] = h1.astype(jnp.int32)
        h2_ref[s, 0] = h2.astype(jnp.int32)
        b1_ref[s, 0] = b1.astype(jnp.int32)
        b2_ref[s, 0] = b2.astype(jnp.int32)
        compat_ref[s, 0] = compat


@functools.partial(jax.jit,
                   static_argnames=("L", "NB", "interpret"))
def shape_fold_pallas(topics: jax.Array, lens: jax.Array,
                      is_dollar: jax.Array, spm: jax.Array,
                      slen: jax.Array, shh: jax.Array, swr: jax.Array,
                      *, L: int, NB: int, interpret: bool = None):
    """Fused fold: -> (h1, h2, b1, b2, compat) each [B, NSc] int32."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    B = topics.shape[0]
    NSc = spm.shape[0]
    # lanes shrink for small batches (min native tile 8x128) so a 257-row
    # call pads to 1024, not SB*BL_MAX=4096
    BL = min(BL_MAX, max(128, 1 << max(0, (-(-B // SB) - 1).bit_length())))
    blk = SB * BL
    nb = max(1, -(-B // blk))
    Bp = nb * blk
    if Bp != B:
        topics = jnp.pad(topics, ((0, Bp - B), (0, 0)))
        lens = jnp.pad(lens, (0, Bp - B))
        is_dollar = jnp.pad(is_dollar, (0, Bp - B))
    # lane-major staging: levels become rows, the batch becomes the
    # [SB, BL] native tile (cheap XLA transposes/reshapes around the
    # kernel, full VPU occupancy inside it)
    topics4 = topics.T.reshape(L, nb, SB, BL)
    lens3 = lens.astype(jnp.int32).reshape(nb, SB, BL)
    dollar3 = is_dollar.astype(jnp.int32).reshape(nb, SB, BL)

    grid = (nb,)
    out_shape = [jax.ShapeDtypeStruct((NSc, nb, SB, BL), jnp.int32)] * 5
    obspec = pl.BlockSpec((NSc, 1, SB, BL), lambda i: (0, i, 0, 0),
                          memory_space=pltpu.VMEM)
    sspec = pl.BlockSpec(memory_space=pltpu.SMEM)
    h1, h2, b1, b2, compat = pl.pallas_call(
        functools.partial(_fold_kernel, L, NB, NSc, BL),
        out_shape=out_shape,
        grid=grid,
        in_specs=[
            sspec, sspec, sspec, sspec,
            pl.BlockSpec((L, 1, SB, BL), lambda i: (0, i, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, SB, BL), lambda i: (i, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, SB, BL), lambda i: (i, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[obspec] * 5,
        interpret=interpret,
    )(spm, slen, shh, swr, topics4, lens3, dollar3)

    def back(x):        # [NSc, nb, SB, BL] -> [B, NSc]
        return x.reshape(NSc, Bp).T[:B]

    return tuple(back(x) for x in (h1, h2, b1, b2, compat))
