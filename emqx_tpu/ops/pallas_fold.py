"""Pallas TPU kernel for the shape-hash fold (the matcher's VPU core).

The shape-directed matcher (ops/shapes.py, replacing the reference's
per-message trie walk, emqx_trie.erl:208-266) spends its compute in a
per-level hash fold over [batch, shapes] lanes followed by two-choice home
bucket derivation and shape-compatibility masking. This kernel fuses the
whole L-level fold, the home computation, and the compatibility mask into
ONE VMEM-resident Pallas program (grid over batch blocks), so the level
loop never materializes intermediates in HBM and the mask/index outputs
come out in a single pass. The two bucket-row gathers stay in XLA (Mosaic
has no large-table vector gather; the gather is HBM-bound either way).

Bit-exactness: identical uint32 arithmetic to the jnp path — the oracle
tests assert h1/h2/compat equality against ops.shapes.shape_match's fold,
so either backend can serve the same tables.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from emqx_tpu.ops.shapes import _fold, _homes, _seed

_U = np.uint32


def _fold_kernel(L: int, NB: int, topics_ref, lens_ref, dollar_ref,
                 spm_ref, slen_ref, shh_ref, swr_ref,
                 h1_ref, h2_ref, b1_ref, b2_ref, compat_ref):
    Bb = topics_ref.shape[0]
    NSc = spm_ref.shape[1]
    sid = jax.lax.broadcasted_iota(jnp.int32, (Bb, NSc), 1)
    h1 = _seed(sid, 0x27D4EB2F, 0x165667B1)
    h2 = _seed(sid, 0x85EBCA6B, 0xC2B2AE3D)
    slen = slen_ref[:]                       # [1, NSc]
    pmask = spm_ref[:]
    for l in range(L):
        concrete = (l < slen) & ((pmask >> l) & 1 == 0)
        w = topics_ref[:, l:l + 1].astype(jnp.uint32)
        h1 = jnp.where(concrete, _fold(h1, w, 2 * l), h1)
        h2 = jnp.where(concrete, _fold(h2, w, 2 * l + 1), h2)
    lens_ = lens_ref[:]                      # [Bb, 1]
    # int32 arithmetic throughout: Mosaic cannot truncate i8->i1, so
    # boolean select/and chains must stay integer-typed in-kernel
    len_ok = jnp.where(shh_ref[:] == 1,
                       (lens_ >= slen).astype(jnp.int32),
                       (lens_ == slen).astype(jnp.int32))
    real_shape = (slen >= 0).astype(jnp.int32)
    dollar_block = ((dollar_ref[:] != 0) & (swr_ref[:] == 1)
                    ).astype(jnp.int32)
    nonempty = (lens_ > 0).astype(jnp.int32)
    compat = len_ok * real_shape * (1 - dollar_block) * nonempty
    b1, b2 = _homes(h1, h2, NB)
    h1_ref[:] = h1.astype(jnp.int32)
    h2_ref[:] = h2.astype(jnp.int32)
    b1_ref[:] = b1.astype(jnp.int32)
    b2_ref[:] = b2.astype(jnp.int32)
    compat_ref[:] = compat


@functools.partial(jax.jit,
                   static_argnames=("L", "NB", "block_b", "interpret"))
def shape_fold_pallas(topics: jax.Array, lens: jax.Array,
                      is_dollar: jax.Array, spm: jax.Array,
                      slen: jax.Array, shh: jax.Array, swr: jax.Array,
                      *, L: int, NB: int, block_b: int = 256,
                      interpret: bool = None):
    """Fused fold: -> (h1, h2, b1, b2, compat) each [B, NSc] int32."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    B = topics.shape[0]
    NSc = spm.shape[0]
    Bb = min(block_b, B)
    nb = -(-B // Bb)
    Bp = nb * Bb
    if Bp != B:
        topics = jnp.pad(topics, ((0, Bp - B), (0, 0)))
        lens = jnp.pad(lens, (0, Bp - B))
        is_dollar = jnp.pad(is_dollar, (0, Bp - B))
    out_shape = [jax.ShapeDtypeStruct((Bp, NSc), jnp.int32)] * 5
    grid = (nb,)
    bspec = pl.BlockSpec((Bb, NSc), lambda i: (i, 0),
                         memory_space=pltpu.VMEM)
    sspec = pl.BlockSpec((1, NSc), lambda i: (0, 0),
                         memory_space=pltpu.VMEM)
    h1, h2, b1, b2, compat = pl.pallas_call(
        functools.partial(_fold_kernel, L, NB),
        out_shape=out_shape,
        grid=grid,
        in_specs=[
            pl.BlockSpec((Bb, topics.shape[1]), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((Bb, 1), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((Bb, 1), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            sspec, sspec, sspec, sspec,
        ],
        out_specs=[bspec] * 5,
        interpret=interpret,
    )(topics, lens[:, None].astype(jnp.int32),
      is_dollar[:, None].astype(jnp.int32),
      spm[None, :], slen[None, :], shh[None, :], swr[None, :])
    return (h1[:B], h2[:B], b1[:B], b2[:B], compat[:B])
