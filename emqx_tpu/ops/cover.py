"""Subscription covering: match the covering set, expand at fan-out.

Real subscription populations are cover-heavy — `sports/#` covers
`sports/+/score`, which covers `sports/f1/score` (arXiv:1811.07088's
aggregation argument, arXiv:1611.08743's subgrouping): most filters are
semantically redundant for *matching* because some broader filter
already matches a superset of their topics. This op makes the device
matcher exploit that: the NFA/shape tables are built over the COVERING
set only (the maximal filters), and a per-cover expansion CSR — the
same segment shape ops/fanout ships — re-expands each matched cover
into its covered filters right after the match stage, with a linear
per-candidate verification (ops/delta's matcher semantics) so the
expanded result is EXACTLY the full-set match, values and order.

Covering relation (exact emqx_topic.erl match/2 superset semantics):
A covers B iff every topic matching B matches A —

  - trailing `#` in A covers any suffix (incl. none): `a/#` covers `a`,
    `a/b`, `a/+/c`, `a/#`-prefixed filters with deeper prefixes;
  - `+` in A covers a literal or `+` at that level, never a trailing
    `#` (B would match deeper topics A cannot);
  - a literal in A covers only the same literal;
  - root-`$` exclusion: a `$`-rooted literal filter's topics are
    `$`-rooted, which root `+`/`#` never match — so root-wildcard
    filters cover no `$`-rooted filter.

Detection REUSES the oracle-tested matchers instead of bespoke pair
logic: A covers B exactly when A *matches the pseudo-topic* formed by
B's own interned words (trailing `#` dropped, B's `+` riding as the
reserved PLUS word id which only A's `+` branch can consume, B's
`$`-literal root as the is_dollar flag), post-filtered by the trailing
`#` rule (a `#`-filter is only covered by `#`-filters) and
self-exclusion. So covering detection is ONE batched `match_batch` run
of the filter table against itself — vectorized level-wise over the
interned columnar table, sharing semantics with the serving matcher by
construction (oracle: `covers_pair` below vs HostTrie enumeration).

Exactness & order: a matched cover does NOT imply its covered filters
match (`sports/#` matches `sports/golf` but `sports/+/score` does not),
so expansion verifies every candidate against the topic with the
linear level-wise matcher before emitting it. The expanded row is then
sorted by a per-filter ORDER KEY that reproduces the full-set
backend's emission order exactly:

  - trie NFA: (emit step, hash-emission-before-exact, frontier lane) —
    the lane order of ops/match's valid-first compaction is the plus-
    choice bits read LSB-first (exact children sort before plus
    children every step), so the key is
    `((step*2 + is_exact) << level_bits) | plus_bits`;
  - shape tables: shape ids are assigned in ascending `sig_small`
    order (ops/shapes flatnonzero factorization), which is independent
    of the built subset — the key is `sig_small` itself.

With `broker.subscription_covering=0` the full set builds as today;
the on/off twins are bit-identical on delivery counts and per-session
order by construction (oracle + A/B tested).
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import numpy as np

from emqx_tpu.ops.intern import HASH, PAD, PLUS

# order-key packing: plus-choice bits occupy the low `level_bits`;
# (step*2 + class) sits above. 24 level bits + 6 step bits + 1 class
# bit fit int32 — filters deeper than MAX_KEY_LEVELS disable covering
# for the snapshot (they could not ride the key), which is always
# correct: covering is a pure optimization over an exact baseline.
MAX_KEY_LEVELS = 24

_KEY_INVALID = np.int32(0x7FFFFFFF)


class CoverTables(NamedTuple):
    """Device expansion state for one snapshot; a clean JAX pytree.

    exp_start/exp_fid/exp_slot: per-fid expansion CSR. A cover's
      segment is [itself] + its covered filters; covered fids have
      empty segments (they never appear in the covering match set).
      exp_slot is the verify-row index, -1 = pre-verified (the cover's
      own self entry — the base match already proved it).
    vwords/vlens: covered filters' interned level ids for the
      per-candidate linear verification (delta_match semantics).
    order_key: per-fid emission order key, DENSIFIED to ranks at build
      (backend-specific raw keys, see module docstring; ranking is
      order-preserving and keeps the expansion sort in int32).
    out_pad: [M_out] zeros — static carrier of the expanded match-row
      width (match_cap for the trie backend, the FULL set's padded
      shape count for the shapes backend, so the expanded plane is
      exactly as wide as the covering-off twin's).
    cand_pad: [C] zeros — static carrier of the candidate capacity;
      a topic whose matched covers own more than C candidates flags
      overflow and host-routes (counted, never silently dropped).
    app_*: the expansion-CSR APPEND region (cover-set churn): a new
      subscription covered by a built cover lands here — matched on
      device next dispatch, no rebuild. app_root is the owning cover's
      fid (-1 = empty row), app_fid the appended filter's fid,
      app_key its order key (rank_base + arrival index — appended
      filters sort AFTER every built filter, like the off twin's
      overlay delivery order), app_words/app_lens its levels for
      verification.
    """

    exp_start: np.ndarray   # [Fc+1]
    exp_fid: np.ndarray     # [E]
    exp_slot: np.ndarray    # [E]
    vwords: np.ndarray      # [V, L]
    vlens: np.ndarray       # [V]
    order_key: np.ndarray   # [Fc]
    out_pad: np.ndarray     # [M_out]
    cand_pad: np.ndarray    # [C]
    app_root: np.ndarray    # [A]
    app_fid: np.ndarray     # [A]
    app_key: np.ndarray     # [A]
    app_words: np.ndarray   # [A, L]
    app_lens: np.ndarray    # [A]


# ---- pairwise predicate (the oracle's reference implementation) ---------

def covers_pair(wa: list, wb: list, b_dollar: bool = False) -> bool:
    """True iff filter A (interned words `wa`) covers filter B — every
    topic matching B matches A. Returns True for identical filters
    (self-cover); callers exclude by fid. `b_dollar`: B's root level is
    a `$`-prefixed literal (interned ids don't carry the prefix)."""
    la, lb = len(wa), len(wb)
    if la == 0 or lb == 0:
        return False
    a_hash = wa[-1] == HASH
    b_hash = wb[-1] == HASH
    pa = la - (1 if a_hash else 0)
    if a_hash:
        if pa > lb - (1 if b_hash else 0):
            return False
    else:
        # without a trailing '#', A matches exactly-la-level topics: it
        # can cover neither a '#'-filter nor a different-length filter
        if b_hash or la != lb:
            return False
    if b_dollar and wa[0] in (PLUS, HASH):
        return False            # root wildcards never match '$'-topics
    for l in range(pa):
        aw, bw = wa[l], wb[l]
        if aw == PLUS:
            continue            # '+' covers a literal or '+' (never a
            #                     trailing '#', excluded by the prefix
            #                     length check above)
        if aw != bw:
            return False        # literal covers only the same literal
    return True


# ---- order keys ----------------------------------------------------------

def trie_order_keys(words: np.ndarray, lens: np.ndarray) -> np.ndarray:
    """Per-filter emission order key of ops/match.match_batch (see
    module docstring). Requires every filter <= MAX_KEY_LEVELS deep."""
    words = np.asarray(words, np.int32)
    lens = np.asarray(lens, np.int64)
    F = len(lens)
    if F == 0:
        return np.zeros(0, np.int32)
    L = words.shape[1]
    ar = np.arange(F)
    has_hash = words[ar, np.maximum(lens - 1, 0)] == HASH
    plen = lens - has_hash
    bits = np.zeros(F, np.int64)
    for l in range(min(L, int(plen.max(initial=0)))):
        bits |= ((words[:, l] == PLUS) & (l < plen)).astype(np.int64) << l
    step = np.where(has_hash, plen, lens)
    cls = (~has_hash).astype(np.int64)
    key = ((step * 2 + cls) << MAX_KEY_LEVELS) | bits
    return key.astype(np.int32)


def shape_order_keys(words: np.ndarray, lens: np.ndarray) -> np.ndarray:
    """Per-filter `sig_small` — the shapes backend's shape-id order
    (ops/shapes assigns shape ids in ascending sig_small, independent
    of the built subset)."""
    words = np.asarray(words, np.int32)
    lens = np.asarray(lens, np.int64)
    F = len(lens)
    if F == 0:
        return np.zeros(0, np.int32)
    ar = np.arange(F)
    has_hash = (words[ar, np.maximum(lens - 1, 0)] == HASH).astype(np.int64)
    slen = lens - has_hash
    plus_mask = np.zeros(F, np.int64)
    for l in range(min(words.shape[1], int(slen.max(initial=0)))):
        plus_mask |= ((words[:, l] == PLUS)
                      & (l < slen)).astype(np.int64) << l
    sig = plus_mask | (slen << 20) | (has_hash << 25)
    return sig.astype(np.int32)


def full_shape_count(words: np.ndarray, lens: np.ndarray) -> int:
    """Distinct shapes of the FULL filter set — the covering-off twin's
    match-row width driver (the expanded plane must be at least this
    wide so expansion can never overflow where the off twin cannot)."""
    if len(lens) == 0:
        return 0
    return len(np.unique(shape_order_keys(words, lens)))


# ---- detection -----------------------------------------------------------

def detect_covers(words: np.ndarray, lens: np.ndarray,
                  dollar: np.ndarray, *, batch: int = 2048,
                  match_cap: int = 128, frontier_cap: int = 32):
    """Find, per filter, the set of OTHER filters covering it.

    Vectorized via the device NFA over the interned columnar table:
    each filter becomes a pseudo-topic (trailing '#' dropped, '+'
    riding as the PLUS word id, '$'-literal root as is_dollar) matched
    against the trie of the whole set in [batch]-lane dispatches.

    Returns (covers, incomplete): `covers` is a list of int arrays
    (covering fids, self excluded), `incomplete` a bool mask of
    filters whose cover set overflowed a capacity — those are treated
    as uncovered (kept in the covering set; always correct)."""
    import jax.numpy as jnp

    from emqx_tpu.ops.match import match_batch
    from emqx_tpu.ops.trie import build_tables

    words = np.asarray(words, np.int32)
    lens = np.asarray(lens, np.int64)
    dollar = np.asarray(dollar, bool)
    F = len(lens)
    covers: list = [np.zeros(0, np.int64) for _ in range(F)]
    incomplete = np.zeros(F, bool)
    if F == 0:
        return covers, incomplete

    L = words.shape[1]
    ar = np.arange(F)
    has_hash = words[ar, np.maximum(lens - 1, 0)] == HASH
    plen = (lens - has_hash).astype(np.int32)
    pseudo = words.copy()
    pseudo[has_hash, np.maximum(lens[has_hash] - 1, 0)] = PAD

    tables = build_tables(words, lens)
    for lo in range(0, F, batch):
        hi = min(F, lo + batch)
        B = hi - lo
        t = np.full((batch, L), PAD, np.int32)
        t[:B] = pseudo[lo:hi]
        ln = np.zeros(batch, np.int32)
        ln[:B] = plen[lo:hi]
        dl = np.zeros(batch, bool)
        dl[:B] = dollar[lo:hi]
        mr = match_batch(tables, jnp.asarray(t), jnp.asarray(ln),
                         jnp.asarray(dl), frontier_cap=frontier_cap,
                         match_cap=match_cap)
        m = np.asarray(mr.matches[:B])
        ov = np.asarray(mr.overflow[:B])
        for i in range(B):
            fid = lo + i
            if ov[i]:
                incomplete[fid] = True
                continue
            c = m[i][m[i] >= 0].astype(np.int64)
            c = c[c != fid]
            if has_hash[fid] and len(c):
                # a '#'-filter is only covered by '#'-filters; the
                # pseudo-topic also surfaces exact matches of its
                # prefix, which match the prefix but not the suffixes
                c = c[words[c, np.maximum(lens[c] - 1, 0)] == HASH]
            covers[fid] = c
    return covers, incomplete


def assign_owners(covers: list, incomplete: np.ndarray, *,
                  own_budget: int = 256) -> np.ndarray:
    """Pick one covering ROOT per covered filter → owner[fid] (-1 =
    stays in the covering set). Roots are filters nothing covers; a
    covered filter's owner is its smallest-fid covering root (covering
    is transitive, so a maximal cover of B is itself uncovered and
    appears in B's cover set). `own_budget` caps one cover's owned
    count — past it, further covered filters stay roots, bounding the
    per-topic expansion fan (candidate capacity stays honest)."""
    F = len(covers)
    owner = np.full(F, -1, np.int64)
    is_root = np.array([len(c) == 0 for c in covers]) | incomplete
    owned = np.zeros(F, np.int64)
    for fid in range(F):
        if is_root[fid]:
            continue
        for a in sorted(int(x) for x in covers[fid]):
            if is_root[a] and owned[a] < own_budget:
                owner[fid] = a
                owned[a] += 1
                break
    return owner


# ---- table builder -------------------------------------------------------

def build_cover_tables(words: np.ndarray, lens: np.ndarray,
                       owner: np.ndarray, order_key: np.ndarray, *,
                       fid_cap: int, out_width: int, cand_cap: int,
                       verify_cap: Optional[int] = None,
                       append_cap: int = 64) -> CoverTables:
    """Compile owner assignments into device CoverTables (numpy; the
    caller device_puts and registers under the HBM ledger's
    `cover_csr` category). Every filter appears in EXACTLY one
    expansion segment (roots carry themselves + their owned set), so
    the CSR payload is one entry per filter."""
    words = np.asarray(words, np.int32)
    lens = np.asarray(lens, np.int64)
    owner = np.asarray(owner, np.int64)
    order_key = np.asarray(order_key, np.int32)
    F = len(lens)
    L = max(1, words.shape[1] if words.ndim == 2 else 1)
    covered = np.flatnonzero(owner >= 0)
    V = max(1, verify_cap or _next_pow2(max(1, len(covered))))
    if len(covered) > V:
        raise ValueError(f"{len(covered)} covered filters > verify "
                         f"capacity {V}")
    E = max(1, fid_cap)

    # DENSIFY the order keys to ranks: the expansion stage's final
    # ordering runs as ONE single-operand int32 sort of
    # (rank << lane_bits) | lane packed keys (5x faster than stable
    # argsort on the CPU proxy — see cover_expand), so keys must fit a
    # small bit budget. Ranking is order-preserving (equal raw keys ->
    # equal rank; the lane bits reproduce stable-sort tie order), and
    # appended filters take ranks above `rank_base` (they sort after
    # every built filter, mirroring the off-twin's overlay order).
    uniq = np.unique(order_key)
    order_key = np.searchsorted(uniq, order_key).astype(np.int32)

    exp_start = np.zeros(fid_cap + 1, np.int32)
    exp_fid = np.full(E, -1, np.int32)
    exp_slot = np.full(E, -1, np.int32)
    vwords = np.full((V, L), PAD, np.int32)
    vlens = np.zeros(V, np.int32)
    key_pad = np.full(fid_cap, _KEY_INVALID, np.int32)
    key_pad[:F] = order_key

    owned: dict[int, list] = {}
    for b in covered:
        owned.setdefault(int(owner[b]), []).append(int(b))
    slot_of: dict[int, int] = {}
    for s, b in enumerate(int(x) for x in covered):
        slot_of[b] = s
        vwords[s, :lens[b]] = words[b, :lens[b]]
        vlens[s] = lens[b]

    off = 0
    for fid in range(F):
        exp_start[fid] = off
        if owner[fid] >= 0:
            continue                      # covered: empty segment
        exp_fid[off] = fid                # self entry, pre-verified
        exp_slot[off] = -1
        off += 1
        for b in owned.get(fid, ()):
            exp_fid[off] = b
            exp_slot[off] = slot_of[b]
            off += 1
    exp_start[F:] = off

    A = max(1, append_cap)
    return CoverTables(
        exp_start=exp_start, exp_fid=exp_fid, exp_slot=exp_slot,
        vwords=vwords, vlens=vlens, order_key=key_pad,
        out_pad=np.zeros(max(1, out_width), np.int32),
        cand_pad=np.zeros(max(1, cand_cap), np.int32),
        app_root=np.full(A, -1, np.int32),
        app_fid=np.full(A, -1, np.int32),
        app_key=np.zeros(A, np.int32),
        app_words=np.full((A, L), PAD, np.int32),
        app_lens=np.zeros(A, np.int32))


def _next_pow2(x: int) -> int:
    return 1 << max(2, (x - 1).bit_length())


def rank_base(ct: CoverTables) -> int:
    """First free order rank for the append path: built filters hold
    dense ranks 0..rank_base-1 (build_cover_tables), so appended
    filters take rank_base + k and sort after every built filter."""
    valid = ct.order_key[ct.order_key != _KEY_INVALID]
    return int(valid.max()) + 1 if valid.size else 0


# ---- device expansion stage ---------------------------------------------

def _verify_rows(vwords, vlens, sel, topics, lens, is_dollar):
    """Linear wildcard verification of selected filter rows against
    each topic lane: out[b, c] = does filter row sel[b, c] match topic
    b. sel -1 = pre-verified (True). EXACT delta_match/np_filter_match
    semantics: per-level exact-or-'+', trailing-'#' prefix rule,
    root-'$' exclusion, empty rows match nothing."""
    import jax.numpy as jnp

    L = topics.shape[1]
    Lv = vwords.shape[1]
    Lc = min(L, Lv)
    safe = jnp.clip(sel, 0, vwords.shape[0] - 1)
    fl = jnp.where(sel >= 0, vlens[safe], 0)            # [B, C]
    # ONE row gather [B, C, Lv] + broadcast compares: per-level
    # vwords[safe, l] gathers serialize terribly on the CPU proxy (L
    # gather kernels over the same index plane), and this stage sits on
    # the serving critical path
    vrow = vwords[safe]                                 # [B, C, Lv]
    last = jnp.take_along_axis(
        vrow, jnp.clip(fl - 1, 0, Lv - 1)[:, :, None], axis=2)[:, :, 0]
    last_hash = (fl > 0) & (last == HASH)
    plen = fl - last_hash.astype(fl.dtype)
    lvl = jnp.arange(Lc, dtype=fl.dtype)
    head = vrow[:, :, :Lc]
    lvl_ok = ((head == topics[:, None, :Lc]) | (head == PLUS)
              | (lvl >= plen[:, :, None]))
    ok = jnp.all(lvl_ok, axis=2)
    # filter levels beyond the topic width can never verify (the
    # engine builds vwords no wider than the topic planes, so this
    # only guards mismatched callers)
    ok &= plen <= Lc
    len_ok = jnp.where(last_hash, lens[:, None] >= plen,
                       lens[:, None] == fl)
    first = vrow[:, :, 0]
    dskip = is_dollar[:, None] & ((first == PLUS) | (first == HASH))
    res = ok & len_ok & ~dskip & (fl > 0) & (lens > 0)[:, None]
    return jnp.where(sel >= 0, res, True)


def cover_expand(ct: CoverTables, mr, topics, lens, is_dollar):
    """Expand matched covers into the exact full-set MatchResult.

    Runs INSIDE the jitted match stage (ops/match.match_batch /
    ops/shapes.shape_match call this when their tables carry cover
    state): CSR-gather each matched cover's candidates, verify each
    against the topic, merge the append region, and sort by the
    per-filter order key so the output row is bit-identical to the
    covering-off twin's (values AND order). Overflow = base overflow
    | candidate-capacity overflow | true count past the output width
    (the same condition the off twin flags)."""
    import jax.numpy as jnp

    from emqx_tpu.ops.fanout import _segment_expand
    from emqx_tpu.ops.match import MatchResult

    M = ct.out_pad.shape[0]
    C = ct.cand_pad.shape[0]
    A = ct.app_root.shape[0]

    fids, idx, _tot, cand_oflow = _segment_expand(
        ct.exp_start, ct.exp_fid, mr.matches, C)
    slots = jnp.where(idx >= 0, ct.exp_slot[jnp.clip(idx, 0)], -1)
    keys = jnp.where(fids >= 0,
                     ct.order_key[jnp.clip(fids, 0,
                                           ct.order_key.shape[0] - 1)],
                     _KEY_INVALID)
    ok = _verify_rows(ct.vwords, ct.vlens, slots, topics, lens,
                      is_dollar)
    valid = (fids >= 0) & ok

    # append region: entry a rides lane b when its owning cover is in
    # b's match row (A is small — a dense [B, M_in, A] compare)
    live = ct.app_root >= 0
    hit = ((mr.matches[:, :, None] == ct.app_root[None, None, :])
           & (mr.matches >= 0)[:, :, None]).any(axis=1)     # [B, A]
    app_sel = jnp.broadcast_to(
        jnp.arange(A, dtype=jnp.int32)[None, :], hit.shape)
    app_ok = _verify_rows(ct.app_words, ct.app_lens, app_sel, topics,
                          lens, is_dollar)
    app_valid = hit & app_ok & live[None, :]

    cand_fid = jnp.concatenate(
        [fids, jnp.broadcast_to(ct.app_fid[None, :], hit.shape)], axis=1)
    cand_key = jnp.concatenate(
        [keys, jnp.broadcast_to(ct.app_key[None, :], hit.shape)], axis=1)
    cand_valid = jnp.concatenate([valid, app_valid], axis=1)

    # final ordering: keys are dense ranks (build_cover_tables), so
    # (rank << lane_bits) | lane packs into int32 and ONE single-
    # operand sort replaces the stable argsort (5x on the CPU proxy;
    # the lane bits reproduce the stable tie order exactly). rank_bits
    # covers built ranks AND append ranks (rank_base + k <= Fc + A).
    C_tot = C + A
    lane_bits = max(1, (C_tot - 1).bit_length())
    Fc = ct.order_key.shape[0]
    rank_bits = max(2, (Fc + A + 1).bit_length())
    if lane_bits + rank_bits <= 31:
        invalid = jnp.int32((1 << rank_bits) - 1)
        lane = jnp.arange(C_tot, dtype=jnp.int32)
        sk = jnp.where(cand_valid, jnp.minimum(cand_key, invalid),
                       invalid)
        packed = jnp.sort((sk << lane_bits) | lane, axis=1)[:, :M]
        s_ok = (packed >> lane_bits) < invalid
        lanes = packed & jnp.int32((1 << lane_bits) - 1)
        s_fid = jnp.take_along_axis(cand_fid, lanes, axis=1)
    else:   # bit budget exceeded (huge shard): stable argsort fallback
        sort_key = jnp.where(cand_valid, cand_key, _KEY_INVALID)
        order = jnp.argsort(sort_key, axis=1, stable=True)
        s_fid = jnp.take_along_axis(cand_fid, order, axis=1)[:, :M]
        s_ok = jnp.take_along_axis(cand_valid, order, axis=1)[:, :M]
    out = jnp.where(s_ok, s_fid, -1)
    count = cand_valid.sum(axis=1, dtype=jnp.int32)
    overflow = mr.overflow | cand_oflow | (count > M)
    return MatchResult(matches=out, counts=jnp.minimum(count, M),
                       overflow=overflow)


# ---- host-side cover lookup (append path) --------------------------------

def host_covering_roots(root_trie, root_words: dict, words: list,
                        b_dollar: bool) -> list:
    """Built ROOTS covering a new filter, via the same pseudo-topic
    trick over a HostTrie of the covering set (the engine's append
    path: covered new sub → expansion-CSR append, no rebuild).
    `root_words` maps root fid → interned words. Candidates from the
    trie walk are post-checked with `covers_pair` (trailing-'#' rule,
    identity exclusion) so the result is oracle-exact. Returns covering
    root fids; [] means the new filter takes the overlay path."""
    words = list(words)
    b_hash = len(words) > 0 and words[-1] == HASH
    pseudo = words[:-1] if b_hash else words
    fids = root_trie.match(list(pseudo), is_dollar=b_dollar)
    out = []
    for f in fids:
        wa = root_words.get(f)
        if wa is None or list(wa) == words:
            continue
        if covers_pair(list(wa), words, b_dollar):
            out.append(f)
    return out
