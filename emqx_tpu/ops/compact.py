"""Device-side readback compaction: RouteResult planes → one CSR payload.

The route pipeline's `materialize` stage ships the full padded result
planes over the device→host link every window — `[W, B, match_cap]`
matches plus `[W, B, fanout_cap]` row/opts planes plus three
`[W, B, slot_cap]` shared planes — even though the median MQTT publish
matches a handful of filters, so at low fan-out the transfer is >90%
`-1` padding over the slowest link in the system (PR-1 stage spans; the
per-message transfer overhead the edge-broker benchmarking literature
identifies as the scaling cliff — PAPERS.md, and the actual-cardinality
match payloads of the subscription-aggregation line of work).

This op compacts the result ON DEVICE, fused after match + fan-out:
per-message valid-entry counts, a prefix-sum across the batch axis, and
a scatter of every valid entry into one dense payload buffer:

    offsets  [W, B+1] int32   combined per-message payload offsets
    counts3  [W, B, 3] int32  (match, fanout, shared) entry counts
    payload  [W, P]   int32   per message, at offsets[w, i]:
                              [ matched fids   : cm ]
                              [ fan-out rows   : cf ]
                              [ fan-out opts   : cf ]  (int8 widened)
                              [ shared slots   : cs ]
                              [ shared rows    : cs ]
                              [ shared opts    : cs ]  (int8 widened)
    row_overflow [W] bool     a row's total entries exceeded P — the
                              caller reads the DENSE planes for that
                              window instead (they are outputs of the
                              same fused program; transferring them is
                              the fallback, computing them is free)

Bit-identity contract (oracle-tested in tests/test_compact_readback.py):
the valid entries of every plane are preserved IN ORDER. Matches may
carry interior `-1` holes (the shape-hash backend emits at most one
filter per shape SLOT), and hole positions are NOT preserved — but every
consumer is hole-insensitive by construction: fan-out rows are the
concatenation of per-filter segments over valid matches in match order
(holes contribute zero-length segments), and the host consume walks
exactly that concatenation. `cm` equals `match_counts` for both
backends, so delivery decisions and cache rows are unchanged.

Capacity P is a static arg (one XLA program per payload class); callers
quantize it onto a small pow2-multiple ladder sized by an EWMA of recent
window totals (broker/device_engine.py) so recompiles stay bounded.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np


class CompactPlanes(NamedTuple):
    offsets: jax.Array       # [W, B+1] int32
    counts3: jax.Array       # [W, B, 3] int32 (match, fanout, shared)
    payload: jax.Array       # [W, P] int32, -1 where unwritten
    row_overflow: jax.Array  # [W] bool


def _rows_searchsorted(sorted_rows: jax.Array, queries: jax.Array,
                       span: int) -> jax.Array:
    """Per-row searchsorted(side='right') over [R, X] rows with [R, Q]
    queries, as ONE flat searchsorted call: rows are offset-encoded into
    a single monotonic array (row r shifted by r * span, where `span`
    strictly bounds every in-row value AND query). A vmapped per-row
    searchsorted pays a per-row dispatch that measured 5x the flat call
    on XLA CPU. int32 throughout (x64 is disabled repo-wide), so the
    caller's R * span must fit — asserted here at trace time."""
    R, X = sorted_rows.shape
    assert R * span < 2**31, (R, span)
    shift = jnp.arange(R, dtype=jnp.int32)[:, None] * jnp.int32(span)
    enc = (sorted_rows + shift).reshape(-1)
    q = (queries + shift).reshape(-1)
    flat = jnp.searchsorted(enc, q, side="right").astype(jnp.int32)
    # flat indexes the concatenated rows; rebase to in-row indices
    return (flat.reshape(R, -1)
            - jnp.arange(R, dtype=jnp.int32)[:, None] * X)


def compact_result(matches: jax.Array, rows: jax.Array, opts: jax.Array,
                   fan_counts: jax.Array, shared_sids: jax.Array,
                   shared_rows: jax.Array, shared_opts: jax.Array, *,
                   payload_cap: int,
                   match_holes: bool = True) -> CompactPlanes:
    """Compact window-stacked RouteResult planes ([W, B, ...]) into CSR.

    GATHER formulation: for each payload slot the owning message comes
    from one searchsorted over the per-row offset ends (the same
    output-driven pattern as ops/fanout._segment_expand), the family
    from comparing the in-message offset against the (cm, cf, cs)
    boundaries, and the value from one fancy gather per family. A
    scatter formulation (valid entries → destinations) lowers to a
    serial bounds-checked loop on XLA CPU and measured 14ms/window at
    B=1024 — ~20x the route step it compacts; the gather form is
    ~0.7ms and vectorizes on every backend.

    Every plane's valid entries are a PREFIX except `matches` on the
    shape-hash backend (one filter per shape SLOT → interior holes),
    closed with a rank→position searchsorted over the validity cumsum —
    valid ids keep their match order, which is the order fan-out
    segments concatenate in. The trie backend emits prefix-compacted
    matches already: pass `match_holes=False` (static) and the whole
    hole-closing stage compiles away.
    """
    W, B, M = matches.shape
    D = rows.shape[-1]
    K = shared_sids.shape[-1]
    P = payload_cap

    valid_m = matches >= 0                                   # [W, B, M]
    cm = valid_m.sum(-1, dtype=jnp.int32)                    # [W, B]
    cf = jnp.minimum(fan_counts, D).astype(jnp.int32)
    cs = (shared_sids >= 0).sum(-1, dtype=jnp.int32)

    n = cm + 2 * cf + 3 * cs
    ends = jnp.cumsum(n, axis=1)                             # [W, B]
    offsets = jnp.pad(ends, ((0, 0), (1, 0)))                # [W, B+1]
    row_overflow = ends[:, -1] > P
    base = offsets[:, :-1]                                   # [W, B]

    if match_holes:
        # hole-compact: position of the (k+1)-th valid entry per row is
        # searchsorted_left(cumsum(valid), k+1) == searchsorted_right(·, k)
        cv = jnp.cumsum(valid_m, axis=-1, dtype=jnp.int32)
        ks = jnp.broadcast_to(jnp.arange(M, dtype=jnp.int32), (W * B, M))
        pos = _rows_searchsorted(cv.reshape(W * B, M), ks, M + 1)
        pos = jnp.minimum(pos, M - 1).reshape(W, B, M)
        mcomp = jnp.take_along_axis(matches, pos, axis=-1)
        mcomp = jnp.where(
            jnp.arange(M, dtype=jnp.int32) < cm[..., None], mcomp, -1)
    else:
        mcomp = matches      # trie NFA output: already prefix-compacted

    opts32 = opts.astype(jnp.int32)
    sopts32 = shared_opts.astype(jnp.int32)

    j = jnp.broadcast_to(jnp.arange(P, dtype=jnp.int32), (W, P))
    span = max(P, B * (M + 2 * D + 3 * K)) + 1
    i = jnp.minimum(_rows_searchsorted(ends, j, span), B - 1)  # [W, P]
    w_ix = jnp.arange(W, dtype=jnp.int32)[:, None]
    jj = j - jnp.take_along_axis(base, i, axis=1)
    in_pay = j < ends[:, -1:]

    def g(plane, col):
        colc = jnp.clip(col, 0, plane.shape[-1] - 1)
        return plane[w_ix, i, colc]

    cm_i = jnp.take_along_axis(cm, i, axis=1)
    cf_i = jnp.take_along_axis(cf, i, axis=1)
    cs_i = jnp.take_along_axis(cs, i, axis=1)
    c1 = cm_i
    c2 = c1 + cf_i
    c3 = c2 + cf_i
    c4 = c3 + cs_i
    c5 = c4 + cs_i
    val = jnp.where(
        jj < c1, g(mcomp, jj),
        jnp.where(jj < c2, g(rows, jj - c1),
                  jnp.where(jj < c3, g(opts32, jj - c2),
                            jnp.where(jj < c4, g(shared_sids, jj - c3),
                                      jnp.where(jj < c5,
                                                g(shared_rows, jj - c4),
                                                g(sopts32, jj - c5))))))
    pay = jnp.where(in_pay, val, -1)

    counts3 = jnp.stack([cm, cf, cs], axis=-1)
    return CompactPlanes(offsets=offsets, counts3=counts3, payload=pay,
                         row_overflow=row_overflow)


@functools.partial(jax.jit,
                   static_argnames=("payload_cap", "match_holes"))
def compact_planes_jit(matches, rows, opts, fan_counts, shared_sids,
                       shared_rows, shared_opts, *, payload_cap: int,
                       match_holes: bool = True) -> CompactPlanes:
    """Standalone jitted compaction over [B, R, ...] mesh planes.

    The mesh readback (parallel/serving.py) compacts as a SECOND small
    dispatch — acceptable on co-located devices where the launch cost is
    microseconds, unlike the relay path where compaction must ride
    inside the route program (models/router_engine.route_*_compact).
    Planes are reshaped to one [1, B*R] pseudo-window so the same op and
    the same host-side decode serve both engines; lane index = i*R + r.
    """
    def flat(a):
        return a.reshape((1, a.shape[0] * a.shape[1]) + a.shape[2:])

    return compact_result(flat(matches), flat(rows), flat(opts),
                          flat(fan_counts), flat(shared_sids),
                          flat(shared_rows), flat(shared_opts),
                          payload_cap=payload_cap,
                          match_holes=match_holes)


def csr_slices(off_row: np.ndarray, c3_row: np.ndarray,
               pay_row: np.ndarray, i: int):
    """Host-side decode: message i's (matches, rows, opts, shared_sids,
    shared_rows, shared_opts) views into one window row's flat payload.
    Slices are views — zero copies on the consume path."""
    o = int(off_row[i])
    cm, cf, cs = (int(x) for x in c3_row[i])
    m = pay_row[o:o + cm]
    r = pay_row[o + cm:o + cm + cf]
    op = pay_row[o + cm + cf:o + cm + 2 * cf]
    s0 = o + cm + 2 * cf
    return (m, r, op, pay_row[s0:s0 + cs], pay_row[s0 + cs:s0 + 2 * cs],
            pay_row[s0 + 2 * cs:s0 + 3 * cs])
