"""Device-side ops: interning, columnar trie, batched NFA match, fan-out.

This package is the TPU-native replacement for the reference's per-message
trie walk (emqx_trie.erl:208-266) and subscriber fold (emqx_broker.erl:282-308):
topic levels are dictionary-encoded to int32 ids, the wildcard-filter trie is
compiled to flat device arrays (hash-table edges + per-node '+'/'#' slots),
and PUBLISH matching runs as a level-stepped batched NFA under jit/vmap,
sharded over filter space with shard_map.
"""
