"""Device-resident delta overlay: post-snapshot filters matched on device.

The compiled trie/shape tables are an immutable snapshot; filters
subscribed AFTER the build used to live in a host-side `HostTrie` and
dispatch host-side entirely until the next full rebuild
(broker/device_engine.py's delta scheme) — so under subscribe churn the
device path degrades to host speed exactly when the broker is busiest
(the churn cliff the broker-benchmarking literature keeps measuring;
PAPERS.md arXiv:1811.07088 §6, arXiv:2603.21600). This op closes that
hole: the post-snapshot filters live in a SMALL flat overlay table —
encoded level ids in the same intern word-id space as the main tables,
plus a per-row fan-out CSR — and a vmapped linear matcher runs them
against every publish lane inside the SAME fused route program
(models/router_engine.route_*_delta), so a subscription landing one
window ago is matched on device in the same dispatch.

A linear matcher (every topic × every overlay row) is the right shape
here, NOT another NFA: the overlay is bounded by the compaction policy
to a few hundred rows (pow2 row classes, broker/device_engine.py), so
the scan is a [B, C] dense op over L levels — trivially vectorizable,
no frontier state, no hash probes — and the table rebuilds host-side in
microseconds on every subscribe instead of the O(N) world recapture.

Match semantics are EXACTLY emqx_topic.erl match/2, same as the main
NFA (ops/match.py) and `HostTrie` (oracle-tested against both):

  - per level: exact word id or '+'; a trailing '#' matches >= 0
    remaining levels ("sport/# matches sport");
  - root-'$' exclusion: topics whose first level starts with '$' skip
    filters whose FIRST level is '+' or '#';
  - unseen publish words encode to UNKNOWN (ops/intern.py) and can only
    match wildcards — identical to the main tables by construction.

Emitted matches are overlay ROW indices (prefix-compacted, -1 pad), the
engine's delta fids ride in `DeltaTables.fids` for host attribution.
Fan-out expansion reuses ops/fanout._segment_expand over the overlay's
own CSR; rows are session rows + packed subopts exactly like the main
`SubTable` planes, so the consume walk is shared.
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from emqx_tpu.ops.fanout import _segment_expand
from emqx_tpu.ops.intern import HASH, PAD, PLUS
from emqx_tpu.ops.match import MatchResult


class DeltaTables(NamedTuple):
    """Flat device overlay of post-snapshot filters; a clean JAX pytree.

    levels: [C, L] int32 encoded filter level ids ('+'/'#' as the
            reserved PLUS/HASH ids, PAD beyond lens[c]).
    lens:   [C] int32 level counts; 0 = empty row (matches nothing).
    fids:   [C] int32 engine delta fid per row (-1 = empty) — host
            attribution only, never compared on device.
    sub_start/sub_row/sub_opts: per-row fan-out CSR (session rows +
            packed subopts, the SubTable planes' delta twin). Rows with
            host-side delivery (rich subopts / oversized fan-out) keep
            an EMPTY segment — they still match on device.
    """

    levels: jax.Array      # [C, L]
    lens: jax.Array        # [C]
    fids: jax.Array        # [C]
    sub_start: jax.Array   # [C+1]
    sub_row: jax.Array     # [S]
    sub_opts: jax.Array    # [S] int8


class DeltaPlanes(NamedTuple):
    """Per-lane overlay output planes (the delta twin of RouteResult's
    match + fan-out families; shared subs never ride the overlay — a
    post-snapshot shared group dispatches host-side via the existing
    handled-set sweep)."""

    fids: jax.Array        # [N, Dm] delta fids in match order (-1 pad)
    counts: jax.Array      # [N] true delta match count
    moverflow: jax.Array   # [N] match-capacity overflow (pre-fan-out)
    rows: jax.Array        # [N, Dc] fan-out session rows (-1 pad)
    opts: jax.Array        # [N, Dc] packed subopts
    fan_counts: jax.Array  # [N] true fan-out entry count
    overflow: jax.Array    # [N] combined (match | fan-out) overflow


def delta_match(dt: DeltaTables, topics: jax.Array, lens: jax.Array,
                is_dollar: jax.Array, *, match_cap: int) -> MatchResult:
    """Linear wildcard match of [N] topic lanes against [C] overlay rows.

    Returns a MatchResult whose `matches` are overlay ROW indices
    (ascending = overlay insertion order), prefix-compacted like the
    trie NFA's output. Scans the level axis with [N, C] carries (the
    same time-axis choice as ops/match.match_batch) so peak memory is
    [N, C], never [N, C, L].
    """
    N, L = topics.shape
    C = dt.levels.shape[0]
    rows = jnp.arange(N, dtype=jnp.int32)[:, None]

    flen = dt.lens                                          # [C]
    last = jnp.take_along_axis(
        dt.levels, jnp.maximum(flen - 1, 0)[:, None], axis=1)[:, 0]
    last_hash = (flen > 0) & (last == HASH)                 # [C]
    # prefix to verify level-by-level: everything before the '#'
    plen = flen - last_hash.astype(jnp.int32)               # [C]

    def step(ok, xs):
        l, w = xs                                           # w: [N]
        fw = dt.levels[:, l]                                # [C]
        lvl_ok = (fw[None, :] == w[:, None]) | (fw == PLUS)[None, :]
        need = (l < plen)[None, :]
        return ok & (~need | lvl_ok), None

    ok0 = jnp.ones((N, C), bool)
    steps = jnp.arange(L, dtype=jnp.int32)
    ok, _ = jax.lax.scan(step, ok0, (steps, topics.T))

    len_ok = jnp.where(last_hash[None, :],
                       lens[:, None] >= plen[None, :],
                       lens[:, None] == flen[None, :])
    first = dt.levels[:, 0]
    dollar_skip = is_dollar[:, None] \
        & ((first == PLUS) | (first == HASH))[None, :]
    valid = (ok & len_ok & ~dollar_skip
             & (flen > 0)[None, :] & (dt.fids >= 0)[None, :]
             & (lens > 0)[:, None])                          # [N, C]

    counts = valid.sum(-1, dtype=jnp.int32)                  # [N]
    pos = jnp.cumsum(valid, axis=1, dtype=jnp.int32) - 1
    pos = jnp.where(valid, pos, match_cap)    # out-of-range → dropped
    out = jnp.full((N, match_cap), -1, jnp.int32)
    col = jnp.broadcast_to(jnp.arange(C, dtype=jnp.int32), (N, C))
    out = out.at[rows, pos].set(col, mode="drop")
    return MatchResult(matches=out, counts=counts,
                       overflow=counts > match_cap)


def delta_expand(dt: DeltaTables, mr: MatchResult, *,
                 fanout_cap: int) -> DeltaPlanes:
    """Expand matched overlay rows into fan-out planes + host fids.

    `mr.overflow` must be MATCH-level only (delta_match's output, or a
    cache-merged base of the same) — the fan-out overflow is recomputed
    here from the CURRENT table, so a membership change between cache
    population and dispatch can never resurrect a stale overflow bit.
    """
    rows, idx, fan_counts, fan_oflow = _segment_expand(
        dt.sub_start, dt.sub_row, mr.matches, fanout_cap)
    opts = jnp.where(idx >= 0, dt.sub_opts[jnp.clip(idx, 0)], jnp.int8(0))
    safe = jnp.clip(mr.matches, 0, dt.fids.shape[0] - 1)
    fids = jnp.where(mr.matches >= 0, dt.fids[safe], -1)
    return DeltaPlanes(fids=fids, counts=mr.counts, moverflow=mr.overflow,
                       rows=rows, opts=opts, fan_counts=fan_counts,
                       overflow=mr.overflow | fan_oflow)


@functools.partial(jax.jit, static_argnames=("match_cap", "fanout_cap"))
def delta_overlay(dt: DeltaTables, topics: jax.Array, lens: jax.Array,
                  is_dollar: jax.Array, *, match_cap: int = 16,
                  fanout_cap: int = 64) -> DeltaPlanes:
    """match + expand in one call (the plain-dispatch composition; the
    cached route programs call the two stages around their base-row
    merge instead — models/router_engine.route_*_delta*)."""
    return delta_expand(dt, delta_match(dt, topics, lens, is_dollar,
                                        match_cap=match_cap),
                        fanout_cap=fanout_cap)


# ---- host-side builder + host-mirror matcher -----------------------------

def build_delta_tables(entries: list, *, row_cap: int, level_cap: int,
                       fan_per_row: int = 8) -> DeltaTables:
    """Compile overlay entries into DeltaTables (numpy; device_put by
    the caller — `broker/device_engine._refresh_overlay` places it and
    registers the placed tree under the HBM ledger's `delta_overlay`
    category, one owner per overlay version, ISSUE 8).

    entries: list of (word_ids, fid, fan) where `fan` is a list of
    (session_row, packed_opts) — pass an EMPTY fan list for rows whose
    delivery must stay host-side (rich subopts, oversized fan-out).
    Capacities are static per (row_cap, level_cap, fan_per_row) class:
    sub rows are `row_cap * fan_per_row` so overlay membership growth
    never changes the jit signature inside a class.
    """
    C, L = row_cap, level_cap
    S = max(1, C * fan_per_row)
    levels = np.full((C, L), PAD, np.int32)
    lens = np.zeros(C, np.int32)
    fids = np.full(C, -1, np.int32)
    sub_start = np.zeros(C + 1, np.int32)
    sub_row = np.full(S, -1, np.int32)
    sub_opts = np.zeros(S, np.int8)
    if len(entries) > C:
        raise ValueError(f"{len(entries)} overlay entries > row cap {C}")
    off = 0
    for c, (words, fid, fan) in enumerate(entries):
        if len(words) > L:
            raise ValueError(f"overlay filter deeper than {L} levels")
        levels[c, :len(words)] = words
        lens[c] = len(words)
        fids[c] = fid
        if len(fan) > fan_per_row:
            raise ValueError(
                f"{len(fan)} fan rows > per-row budget {fan_per_row}")
        sub_start[c] = off
        for sid, opt in fan:
            sub_row[off] = sid
            sub_opts[off] = opt
            off += 1
    sub_start[len(entries):] = off
    return DeltaTables(levels=levels, lens=lens, fids=fids,
                       sub_start=sub_start, sub_row=sub_row,
                       sub_opts=sub_opts)


def np_filter_match(words: list, enc: np.ndarray, lens: np.ndarray,
                    dollar: np.ndarray) -> np.ndarray:
    """Host-mirror of delta_match for ONE filter against [N] encoded
    topics: the delta-aware match-cache invalidation check
    (broker/match_cache.py drop_where) — a new/deleted overlay filter
    drops exactly the cached topics it matches, nothing else. Must stay
    semantics-identical to delta_match above (oracle-tested)."""
    fl = len(words)
    if fl == 0:
        return np.zeros(len(lens), bool)
    last_hash = words[-1] == HASH
    plen = fl - (1 if last_hash else 0)
    ok = lens > 0
    if last_hash:
        ok &= lens >= plen
    else:
        ok &= lens == fl
    for l in range(min(plen, enc.shape[1])):
        if words[l] != PLUS:
            ok &= enc[:, l] == words[l]
    if words[0] in (PLUS, HASH):
        ok &= ~dollar.astype(bool)
    return ok


def empty_delta_tables(row_cap: int, level_cap: int,
                       fan_per_row: int = 8) -> DeltaTables:
    return build_delta_tables([], row_cap=row_cap, level_cap=level_cap,
                              fan_per_row=fan_per_row)
