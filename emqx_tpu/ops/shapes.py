"""Shape-directed wildcard matching: the fast TPU path.

Insight: a wildcard filter is its *shape* (which levels are '+', whether it
ends in '#', how many concrete levels) plus the concrete words. Filters are
grouped by shape into one bucketed hash table keyed by (shape, concrete-word
path hash). Matching a topic then costs, per candidate shape, a dense VPU
hash fold over the topic's levels plus ONE bucket row-gather — instead of the
trie NFA's per-level frontier probes. On the reference's own bench shape
(`device/{{id}}/+/{{num}}/#`, emqx_broker_bench.erl:25-34) there is exactly
one shape, so matching is one gather per topic.

This replaces the same reference hot path as ops/match.py (emqx_trie.erl
do_match :208-266) with identical semantics (root-'$' exclusion, '#' matches
zero levels); the trie NFA remains the fallback for filter sets with more
distinct shapes than SHAPE_CAP. Match results are filter-id lists compatible
with ops/fanout.py.

Collision safety: 2x32-bit path hashes + shape-compatibility check; a false
match needs a 64-bit collision within one shape (~2^-64 per pair).
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from emqx_tpu.ops.intern import HASH, PLUS
from emqx_tpu.ops.match import MatchResult

BK = 8                  # filter entries per bucket (one row-gather wide)
DEFAULT_SHAPE_CAP = 32  # max distinct shapes per table

_U = np.uint32


def _fold(h, w, l: int):
    """One hash-fold step; identical under numpy and jax.numpy (uint32)."""
    h = h ^ (w * _U(0x85EBCA77) + _U((l * 0x9E3779B1) & 0xFFFFFFFF))
    h = h * _U(0xC2B2AE35)
    return h ^ (h >> _U(15))


def _fin(h):
    h = h ^ (h >> _U(16))
    h = h * _U(0x7FEB352D)
    return h ^ (h >> _U(13))


def _seed(shape_id, c1: int, c2: int):
    return _fin(shape_id.astype("uint32") * _U(c1) + _U(c2))


class ShapeTables(NamedTuple):
    """Compiled shape-partitioned filter store (all int32; a JAX pytree).

    shape_plus_mask: [NS] bit l set = level l is '+'.
    shape_len: [NS] concrete level count (excluding trailing '#'); -1 = pad.
    shape_has_hash: [NS] 1 if the shape ends in '#'.
    shape_wild_root: [NS] 1 if level 0 is '+' or the shape is bare '#'
      (excluded for '$'-rooted topics, emqx_topic.erl:66-69).
    buckets: [NB, 3*BK] rows of h1[BK] | h2[BK] | fid[BK], fid -1 = empty.
      Two-choice bucketized hash table: every filter lives in one of its two
      home buckets, so a lookup is exactly two row-gathers. Pre-sized to
      ~0.7 load (NB*BK >= F/0.7) — greedy two-choice placement keeps the
      per-bucket max well under BK without a grow-retry loop, at ~1.7x the
      raw (h1,h2,fid) payload instead of round 1's ~6.7x.
    """

    shape_plus_mask: np.ndarray
    shape_len: np.ndarray
    shape_has_hash: np.ndarray
    shape_wild_root: np.ndarray
    buckets: np.ndarray
    n_shapes: np.ndarray
    n_filters: np.ndarray
    # optional subscription-covering expansion state (ops/cover): when
    # present the buckets hold the COVERING set only and shape_match
    # re-expands matched covers into the exact full-set result, padded
    # to the FULL set's shape width (cover.out_pad) so the covering-off
    # twin's match_width is preserved. None = empty pytree node.
    cover: Optional[NamedTuple] = None


class ShapeCapacityError(ValueError):
    """Filter set has more distinct shapes than the table capacity."""


def _next_pow2(x: int) -> int:
    return 1 << max(2, (x - 1).bit_length())


def _homes(h1, h2, nb):
    """Two home buckets per item (identical under numpy and jax.numpy)."""
    b1 = _fin(h1 ^ (h2 * _U(0x9E3779B1))) & _U(nb - 1)
    b2 = _fin(h2 ^ (h1 * _U(0x85EBCA77))) & _U(nb - 1)
    return b1, b2


def _homes_host(h1: np.ndarray, h2: np.ndarray, nb: int):
    """_homes with in-place uint32 arithmetic (host build only; identical
    results — the device/_fold_xla path keeps the functional version)."""
    out = []
    for a, b, c in ((h1, h2, 0x9E3779B1), (h2, h1, 0x85EBCA77)):
        x = b * _U(c)
        x ^= a
        tmp = x >> _U(16)
        x ^= tmp
        x *= _U(0x7FEB352D)
        np.right_shift(x, _U(13), out=tmp)
        x ^= tmp
        x &= _U(nb - 1)
        out.append(x)
    return out[0], out[1]


def _place(home1: np.ndarray, home2: np.ndarray, nb: int):
    """Assign each item a (bucket, rank<BK) among its two homes, vectorized.

    Sort-free scatter race: each round, every pending item hashes to one of
    its 2*BK candidate positions (bucket choice x slot) and claims it with a
    last-writer-wins scatter; a re-gather identifies the winner. O(F) per
    round with shrinking rounds; a sequential cuckoo-eviction pass seats the
    tiny tail (~0.03% at 0.7 load). Returns (bucket, rank, leftover) —
    leftover is empty on success.

    Round 0 (the whole array) is special-cased: the table is empty, so the
    free-slot test and index compression are skipped — one scatter + one
    winner re-gather instead of three random passes (the single-core build
    budget at 10M filters is tight, round-2 weak #8).
    """
    F = len(home1)
    h1_32 = np.ascontiguousarray(home1).view(np.int32) \
        if home1.dtype == np.uint32 else home1.astype(np.int32)
    h2_32 = np.ascontiguousarray(home2).view(np.int32) \
        if home2.dtype == np.uint32 else home2.astype(np.int32)
    pos_tab = np.full(nb * BK, -1, np.int32)
    # round 0: everyone claims (b1, slot h2&7) in one fused expression —
    # one random scatter + one random gather over the whole array; the
    # slot bits come free from h2, no probe-seed pass needed yet
    cand = (h1_32 << 3) | (h2_32 & (BK - 1))
    pending = np.arange(F, dtype=np.int32)
    pos_tab[cand] = pending              # all slots empty: claim directly
    lost = pos_tab[cand] != pending
    # carry compressed per-item arrays through the remaining rounds: the
    # survivors shrink ~4x per round, and compressing beats re-gathering
    # pref[pending]/h1[pending]/h2[pending] randomly each round
    pending = pending[lost]
    p1 = h1_32[lost]
    p2 = h2_32[lost]
    pref = p1 * 0x9E37 + p2 * 0x85EB     # per-item probe-order seed
    for r in range(1, 2 * BK):           # one round per candidate position
        if len(pending) == 0:
            break
        k = (pref + r) & (2 * BK - 1)
        choice = np.where(k & 1 == 0, p1, p2)
        cand = choice * BK + (k >> 1)
        free = pos_tab[cand] == -1
        cf, pf = cand[free], pending[free]
        pos_tab[cf] = pf
        lost = np.ones(len(pending), bool)
        lost[np.flatnonzero(free)[pos_tab[cf] == pf]] = False
        pending = pending[lost]
        p1, p2, pref = p1[lost], p2[lost], pref[lost]
    # one merged random scatter of the flat position, then two sequential
    # unpack passes (bucket = pos >> 3, rank = pos & 7 for BK == 8)
    combined = np.full(F, -1, np.int32)
    filled = np.flatnonzero(pos_tab >= 0).astype(np.int32)
    combined[pos_tab[filled]] = filled
    placed = combined >= 0
    bucket = np.where(placed, combined >> 3, -1)
    rank = np.where(placed, combined & 7, -1)
    if len(pending) == 0:
        return bucket, rank, pending
    return _place_evict(bucket, rank, pending, home1, home2,
                        pos_tab.reshape(nb, BK))


_MAX_KICKS = 500


def _place_evict(bucket, rank, pending, home1, home2, slots):
    """Cuckoo random-walk eviction for items whose candidate slots all lost.

    Sequential (host) — only runs on the straggler tail the scatter rounds
    could not seat. Deterministic: the victim slot rotates with the walk
    step."""
    still = []
    for it in pending:
        cur = int(it)
        b = int(home1[cur])
        for step in range(_MAX_KICKS):
            row = slots[b]
            free = np.flatnonzero(row == -1)
            if len(free):
                r = int(free[0])
                slots[b, r] = cur
                bucket[cur], rank[cur] = b, r
                cur = -1
                break
            v_slot = (cur + step) % BK
            victim = int(slots[b, v_slot])
            slots[b, v_slot] = cur
            bucket[cur], rank[cur] = b, v_slot
            cur = victim
            b = int(home1[cur]) if b == home2[cur] else int(home2[cur])
        if cur >= 0:
            bucket[cur], rank[cur] = -1, -1
            still.append(cur)
    return bucket, rank, np.array(still, np.int64)


def _fold_into(h: np.ndarray, w: np.ndarray, l: int,
               tmp: np.ndarray) -> None:
    """In-place _fold (host only): identical uint32 arithmetic, no
    intermediate allocations — the fold is memory-bound at 10M filters."""
    np.multiply(w, _U(0x85EBCA77), out=tmp)
    tmp += _U((l * 0x9E3779B1) & 0xFFFFFFFF)
    h ^= tmp
    h *= _U(0xC2B2AE35)
    np.right_shift(h, _U(15), out=tmp)
    h ^= tmp


def _path_hashes(wordsT: np.ndarray, slen, plus_mask, seeds1, seeds2):
    """Fold concrete-word hashes over levels. wordsT [L, N] (transposed so
    each level is a contiguous row — the [N, L] column reads were paying
    ~4x memory traffic at 10M filters); others [N].

    Host-side fast paths (bit-identical to _fold/_fold_xla): levels where
    no item is concrete are skipped, levels where every item is concrete
    fold in place without the where-merge; the mixed case folds a copy and
    merges masked.
    """
    h1 = np.asarray(seeds1).astype(np.uint32, copy=True)
    h2 = np.asarray(seeds2).astype(np.uint32, copy=True)
    N = len(h1)
    L = wordsT.shape[0] if wordsT.ndim == 2 else 0
    L = min(L, int(np.max(slen, initial=0)))  # no concrete words beyond max slen
    tmp = np.empty(N, np.uint32)
    for l in range(L):
        concrete = (l < slen) & ((plus_mask >> l) & 1 == 0)
        n_conc = int(np.count_nonzero(concrete))
        if n_conc == 0:
            continue
        w = wordsT[l].view(np.uint32)
        if n_conc == N:
            _fold_into(h1, w, 2 * l, tmp)
            _fold_into(h2, w, 2 * l + 1, tmp)
        else:
            for h, ll in ((h1, 2 * l), (h2, 2 * l + 1)):
                folded = h.copy()
                _fold_into(folded, w, ll, tmp)
                np.copyto(h, folded, where=concrete)
    return h1, h2


def build_shape_tables(words: np.ndarray, lens: np.ndarray,
                       filter_ids: Optional[np.ndarray] = None,
                       shape_cap: int = DEFAULT_SHAPE_CAP,
                       bucket_capacity: Optional[int] = None) -> ShapeTables:
    """Compile a deduplicated filter set into ShapeTables (host, vectorized).

    words: [F, L] interned level ids (PAD beyond lens); lens: [F] (>=1).
    Raises ShapeCapacityError when distinct shapes exceed shape_cap (caller
    falls back to the trie NFA backend).
    """
    words = np.asarray(words, np.int32)
    lens = np.asarray(lens, np.int64)
    F = len(lens)
    if filter_ids is None:
        filter_ids = np.arange(F)
    filter_ids = np.asarray(filter_ids, np.int64)

    if F == 0:
        NSc = 1
        return ShapeTables(
            shape_plus_mask=np.zeros(NSc, np.int32),
            shape_len=np.full(NSc, -1, np.int32),
            shape_has_hash=np.zeros(NSc, np.int32),
            shape_wild_root=np.zeros(NSc, np.int32),
            buckets=np.concatenate([np.zeros((16, 2 * BK), np.int32),
                                    np.full((16, BK), -1, np.int32)], axis=1),
            n_shapes=np.int32(0), n_filters=np.int32(0))

    L = words.shape[1]
    if L > 20:
        raise ValueError("shape tables support at most 20 levels")
    lens32 = lens.astype(np.int32)
    arangeF = np.arange(F, dtype=np.int32)
    has_hash = (words[arangeF, lens32 - 1] == HASH).astype(np.int32)
    slen = lens32 - has_hash
    # one transpose pass makes every level a contiguous row for the
    # per-level loops here and in _path_hashes (column reads on [F, L]
    # cost ~4x the memory traffic)
    Lmax = min(L, int(slen.max(initial=0)))
    wordsT = np.ascontiguousarray(words[:, :Lmax].T)
    # per-level accumulation: avoids materializing an [F, L] int64 temp
    plus_mask = np.zeros(F, np.int32)
    for l in range(Lmax):
        plus_mask |= ((wordsT[l] == PLUS)
                      & (l < slen)).astype(np.int32) << l

    # O(F) factorize via a 26-bit lookup table instead of np.unique's sort
    # (plus_mask < 2^20 by the L<=20 guard, slen <= 20 -> 5 bits, has_hash
    # 1 bit); flatnonzero keeps np.unique's sorted-uniq ordering, so shape
    # ids are identical to the previous encoding
    sig_small = plus_mask | (slen << 20) | (has_hash << 25)
    seen = np.zeros(1 << 26, bool)
    seen[sig_small] = True
    uniq_small = np.flatnonzero(seen).astype(np.int64)
    NS = len(uniq_small)
    if NS > shape_cap:
        raise ShapeCapacityError(f"{NS} shapes > cap {shape_cap}")
    # a narrow lut (64MB int8 when NS fits) stays cache-friendlier than a
    # 256MB int32 table for the 10M-gather that follows
    lut_dtype = np.int8 if NS <= 127 else np.int32
    lut = np.zeros(1 << 26, lut_dtype)
    lut[uniq_small] = np.arange(NS, dtype=lut_dtype)
    inv = lut[sig_small]
    del seen, lut
    # re-widen to the canonical sig encoding consumed below
    uniq = ((uniq_small & 0xFFFFF) | (((uniq_small >> 20) & 0x1F) << 24)
            | ((uniq_small >> 25) << 60))
    # pad the shape axis to the next pow2 of the ACTUAL count — every padded
    # shape costs a full [B]-wide bucket gather per match call
    NSc = 1 << max(0, (NS - 1).bit_length())

    shape_plus_mask = np.zeros(NSc, np.int32)
    shape_len = np.full(NSc, -1, np.int32)
    shape_has_hash = np.zeros(NSc, np.int32)
    shape_plus_mask[:NS] = (uniq & 0xFFFFFF).astype(np.int32)
    shape_len[:NS] = ((uniq >> 24) & 0xFFFFFFFF).astype(np.int32)
    shape_has_hash[:NS] = (uniq >> 60).astype(np.int32)
    shape_wild_root = (((shape_plus_mask & 1) == 1)
                       | ((shape_has_hash == 1) & (shape_len == 0))
                       ).astype(np.int32)
    shape_wild_root[shape_len < 0] = 0

    # seeds depend only on the shape id: hash NS values, gather by inv
    sid_u = np.arange(NS, dtype=np.int64)
    s1 = _seed(sid_u, 0x27D4EB2F, 0x165667B1)[inv]
    s2 = _seed(sid_u, 0x85EBCA6B, 0xC2B2AE3D)[inv]
    h1, h2 = _path_hashes(wordsT, slen, plus_mask, s1, s2)

    # pre-size to ~0.7 load: two-choice placement stays collision-free here,
    # so there is no grow-retry loop (round 1 spent 18s growing 16x)
    NB = bucket_capacity or _next_pow2(max(16, -(-F * 10 // (BK * 7))))
    while True:
        b1, b2 = _homes_host(h1, h2, NB)
        bucket, rank, leftover = _place(b1, b2, NB)
        if len(leftover) == 0:
            break
        if bucket_capacity is not None:
            # caller pinned the bucket shape (e.g. for uniform sharded
            # stacking): growing would silently diverge from sibling shards
            err = ShapeCapacityError(
                f"bucket_capacity={bucket_capacity} overflows ("
                f"{len(leftover)} filters unplaceable); rebuild every shard "
                f"with bucket_capacity={2 * NB}")
            err.needed_capacity = 2 * NB
            raise err
        NB *= 2
        if NB > 1 << 28:
            raise MemoryError("shape bucket table too large")

    buckets = np.zeros((NB, 3 * BK), np.int32)
    buckets[:, 2 * BK:] = -1
    # one flat base index; three offset scatters (index math once, not 3x;
    # an interleaved-row scatter + transpose was tried and lost cold — the
    # extra 320MB of fresh pages cost more than the saved cache misses)
    flat = buckets.reshape(-1)
    base = bucket * (3 * BK) + rank      # NB*3*BK < 2^31: int32 safe
    flat[base] = h1.view(np.int32)       # uint32 bit-reinterpret
    flat[base + BK] = h2.view(np.int32)
    flat[base + 2 * BK] = filter_ids.astype(np.int32)

    return ShapeTables(
        shape_plus_mask=shape_plus_mask, shape_len=shape_len,
        shape_has_hash=shape_has_hash, shape_wild_root=shape_wild_root,
        buckets=buckets, n_shapes=np.int32(NS), n_filters=np.int32(F))


def _fold_xla(st: ShapeTables, topics: jax.Array, lens: jax.Array,
              is_dollar: jax.Array):
    """Per-level hash fold + compatibility + homes (the XLA backend).
    -> (h1, h2, b1, b2, compatible), hashes uint32."""
    B, L = topics.shape
    NSc = st.shape_plus_mask.shape[0]
    NB = st.buckets.shape[0]
    sid = jax.lax.broadcasted_iota(jnp.int32, (1, NSc), 1)
    h1 = jnp.broadcast_to(_seed(sid, 0x27D4EB2F, 0x165667B1), (B, NSc))
    h2 = jnp.broadcast_to(_seed(sid, 0x85EBCA6B, 0xC2B2AE3D), (B, NSc))
    slen = st.shape_len[None, :]
    pmask = st.shape_plus_mask[None, :]
    for l in range(L):
        concrete = (l < slen) & ((pmask >> l) & 1 == 0)
        w = topics[:, l:l + 1].astype(jnp.uint32)
        h1 = jnp.where(concrete, _fold(h1, w, 2 * l), h1)
        h2 = jnp.where(concrete, _fold(h2, w, 2 * l + 1), h2)

    lens_ = lens[:, None]
    compatible = jnp.where(st.shape_has_hash[None, :] == 1,
                           lens_ >= slen, lens_ == slen)
    compatible &= slen >= 0
    compatible &= ~(is_dollar[:, None] & (st.shape_wild_root[None, :] == 1))
    compatible &= lens_ > 0  # batch-padding rows match nothing
    b1, b2 = _homes(h1, h2, NB)
    return h1, h2, b1, b2, compatible


def _probe_buckets(st: ShapeTables, h1, h2, b1, b2,
                   compatible) -> MatchResult:
    """Two bucket row-gathers + hash compare (shared by both backends)."""
    B = h1.shape[0]
    h1i = h1.astype(jnp.int32)[..., None]
    h2i = h2.astype(jnp.int32)[..., None]
    compatible = compatible.astype(bool)

    def probe(home):
        rows = st.buckets[home.astype(jnp.int32)]  # [B, NSc, 3*BK] gather
        hit = ((rows[..., :BK] == h1i) & (rows[..., BK:2 * BK] == h2i)
               & (rows[..., 2 * BK:] >= 0) & compatible[..., None])
        idx = jnp.argmax(hit, axis=-1)
        fid = jnp.take_along_axis(rows[..., 2 * BK:], idx[..., None],
                                  axis=-1)[..., 0]
        return hit.any(-1), fid

    hit1, fid1 = probe(b1)
    hit2, fid2 = probe(b2)
    matches = jnp.where(hit1, fid1, jnp.where(hit2, fid2, -1))
    counts = (matches >= 0).sum(axis=-1, dtype=jnp.int32)
    return MatchResult(matches=matches, counts=counts,
                       overflow=jnp.zeros(B, bool))


# fold backend for the serving path: "xla" (default) or "pallas" (the
# lane-major fused kernel, ops/pallas_fold.py). Bit-identical results
# either way (oracle-tested), so this is purely a measured-performance
# switch — flip via env EMQX_TPU_FOLD=pallas after the bench's
# match_pallas_per_s beats match_xla_per_s on the target hardware.
import os as _os


def resolve_fold_backend(configured=None) -> str:
    """The one fold-backend resolution: an explicit value (callers use
    ``set_fold_backend``) beats ``EMQX_TPU_FOLD`` beats ``"xla"``.
    Import-time knob — config cannot reach module import, so the env is
    the deploy-time override; validated so a typo fails loudly instead
    of silently serving the default backend."""
    backend = configured if configured is not None \
        else _os.environ.get("EMQX_TPU_FOLD", "xla")
    if backend not in ("xla", "pallas"):
        raise ValueError(
            f"EMQX_TPU_FOLD={backend!r}: expected 'xla' or 'pallas'")
    return backend


_FOLD_BACKEND = resolve_fold_backend()


# False when the last backend switch could not clear shape_match's jit
# cache: already-traced avals may silently keep serving the OLD fold —
# bench.py records this next to the measured rates so a "winner shipped"
# claim is falsifiable (see fold_backend_effective()).
_FOLD_BACKEND_EFFECTIVE = True


def set_fold_backend(name: str) -> None:
    """Select the fold backend for subsequently TRACED programs (bench.py
    measures both on the live hardware and ships the winner — VERDICT r4
    item 8: 'fold_backend chosen by data'). shape_match's OWN jit cache
    is cleared: it reads the global at trace time, and a stale cached
    jaxpr (populated by the tuning calls themselves) would silently keep
    the old backend for identical avals. Outer programs already jitted
    (route_step_shapes etc.) keep the backend they traced with; call
    before tracing the serving step.

    A clear_cache failure is NOT swallowed silently: it logs a warning
    and flips `fold_backend_effective()` False, so bench rows record
    that the switch may not have taken effect for already-seen shapes."""
    global _FOLD_BACKEND, _FOLD_BACKEND_EFFECTIVE
    if name not in ("xla", "pallas"):
        raise ValueError(f"fold backend {name!r}: expected xla or pallas")
    if name != _FOLD_BACKEND:
        _FOLD_BACKEND = name
        try:
            shape_match.clear_cache()
            _FOLD_BACKEND_EFFECTIVE = True
        except Exception as e:   # noqa: BLE001 — switch degrades, loudly
            _FOLD_BACKEND_EFFECTIVE = False
            import logging
            logging.getLogger("emqx_tpu.shapes").warning(
                "set_fold_backend(%r): shape_match.clear_cache() failed "
                "(%s: %s) — programs already traced keep the previous "
                "fold backend for identical shapes; only NEW shape "
                "classes pick up the switch", name, type(e).__name__, e)


def fold_backend_effective() -> bool:
    """True when the last set_fold_backend() fully took effect (the jit
    cache cleared, so every subsequent trace uses the selected fold)."""
    return _FOLD_BACKEND_EFFECTIVE


def _fold_pallas(st: ShapeTables, topics, lens, is_dollar):
    """The pallas fold with shape_match's calling convention (shared by
    the env-selected serving path and the benchmarked pallas entry)."""
    from emqx_tpu.ops.pallas_fold import shape_fold_pallas
    return shape_fold_pallas(
        topics, lens.astype(jnp.int32), is_dollar,
        st.shape_plus_mask, st.shape_len, st.shape_has_hash,
        st.shape_wild_root, L=topics.shape[1], NB=st.buckets.shape[0])


@jax.jit
def shape_match(st: ShapeTables, topics: jax.Array, lens: jax.Array,
                is_dollar: jax.Array) -> MatchResult:
    """Match a topic batch against all shapes: two bucket gathers per shape.

    Returns MatchResult with matches [B, NS] (each shape contributes at most
    one filter id, -1 otherwise); counts [B]; overflow always False (the
    output is exhaustive by construction: every filter lives in one of its
    two home buckets).
    """
    if _FOLD_BACKEND == "pallas":
        h1, h2, b1, b2, compatible = _fold_pallas(st, topics, lens,
                                                  is_dollar)
    else:
        h1, h2, b1, b2, compatible = _fold_xla(st, topics, lens, is_dollar)
    mr = _probe_buckets(st, h1, h2, b1, b2, compatible)
    return _cover_expand_maybe(st, mr, topics, lens, is_dollar)


def _cover_expand_maybe(st: ShapeTables, mr: MatchResult, topics, lens,
                        is_dollar) -> MatchResult:
    """Subscription covering: when the tables carry cover state, the
    buckets held the covering set only — re-expand matched covers into
    the exact full-set row (fused CSR gather + verify + order-key sort,
    ops/cover). Trace-time branch: covering-off snapshots have a
    different pytree structure, so their programs are unchanged."""
    if st.cover is None:
        return mr
    from emqx_tpu.ops.cover import cover_expand
    return cover_expand(st.cover, mr, topics, lens, is_dollar)


@jax.jit
def shape_match_pallas(st: ShapeTables, topics: jax.Array,
                       lens: jax.Array,
                       is_dollar: jax.Array) -> MatchResult:
    """shape_match with the fold stage as a fused Pallas kernel
    (ops/pallas_fold.py); bit-identical results by construction."""
    h1, h2, b1, b2, compat = _fold_pallas(st, topics, lens, is_dollar)
    mr = _probe_buckets(st, h1, h2, b1, b2, compat)
    return _cover_expand_maybe(st, mr, topics, lens, is_dollar)
