"""Topic-level interning: level strings → dense int32 ids.

TPUs cannot branch on strings; every topic level is dictionary-encoded
host-side before it reaches the device (SURVEY.md §7 "Strings on TPU").
Reserved ids:

  PAD (0)      padding beyond a topic's level count
  PLUS (1)     the '+' wildcard word
  HASH (2)     the '#' wildcard word
  UNKNOWN (3)  a publish-topic word that appears in no filter — it can never
               take an exact trie edge, but still matches '+'/'#'

Dynamic ids start at FIRST_DYNAMIC and are assigned on first sight of a word
in a *filter* (publish topics use lookup(), which never allocates).
"""

from __future__ import annotations

PAD = 0
PLUS = 1
HASH = 2
UNKNOWN = 3
FIRST_DYNAMIC = 4


class InternTable:
    """Host-side word ↔ id map. Not thread-safe; owned by the router's
    single-writer update task (the reference serializes route mutations the
    same way via pooled workers, emqx_broker.erl:427-428)."""

    def __init__(self):
        self._to_id: dict[str, int] = {"+": PLUS, "#": HASH}
        self._to_word: list = [None, "+", "#", None]  # PAD/UNKNOWN unmapped

    def __len__(self) -> int:
        return len(self._to_word)

    def intern(self, word: str) -> int:
        """Get-or-assign an id for a filter word."""
        wid = self._to_id.get(word)
        if wid is None:
            wid = len(self._to_word)
            self._to_id[word] = wid
            self._to_word.append(word)
        return wid

    def lookup(self, word: str) -> int:
        """Id for a publish-topic word; UNKNOWN if never seen in a filter."""
        return self._to_id.get(word, UNKNOWN)

    def word(self, wid: int) -> str:
        w = self._to_word[wid]
        if w is None:
            raise KeyError(f"id {wid} has no word")
        return w

    def encode_filter(self, words: list[str]) -> list[int]:
        return [self.intern(w) for w in words]

    def encode_topic(self, words: list[str]) -> list[int]:
        return [self.lookup(w) for w in words]
