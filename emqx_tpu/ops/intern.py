"""Topic-level interning: level strings → dense int32 ids.

TPUs cannot branch on strings; every topic level is dictionary-encoded
host-side before it reaches the device (SURVEY.md §7 "Strings on TPU").
Reserved ids:

  PAD (0)      padding beyond a topic's level count
  PLUS (1)     the '+' wildcard word
  HASH (2)     the '#' wildcard word
  UNKNOWN (3)  a publish-topic word that appears in no filter — it can never
               take an exact trie edge, but still matches '+'/'#'

Dynamic ids start at FIRST_DYNAMIC and are assigned on first sight of a word
in a *filter* (publish topics use lookup(), which never allocates).
"""

from __future__ import annotations

PAD = 0
PLUS = 1
HASH = 2
UNKNOWN = 3
FIRST_DYNAMIC = 4


class InternTable:
    """Host-side word ↔ id map. Not thread-safe; owned by the router's
    single-writer update task (the reference serializes route mutations the
    same way via pooled workers, emqx_broker.erl:427-428)."""

    def __init__(self):
        self._to_id: dict[str, int] = {"+": PLUS, "#": HASH}
        self._to_word: list = [None, "+", "#", None]  # PAD/UNKNOWN unmapped
        # native mirror (SURVEY §7 hard-part 3): word→id replicated into
        # the C library (hash-probed, word bytes confirmed by memcmp —
        # correctness never touches hash uniqueness) so publish batches
        # encode in one native call. None = not yet attached; False =
        # permanently retired (library absent, handles exhausted, or an
        # allocation failure)
        self._mirror: "int | None | bool" = None

    def __len__(self) -> int:
        return len(self._to_word)

    def __del__(self):   # release the C-side handle with the table
        m = getattr(self, "_mirror", None)
        if isinstance(m, int):
            try:
                from emqx_tpu import native
                native.intern_mirror_free(m)
            except Exception:   # noqa: BLE001 — interpreter teardown
                pass

    def _attach_mirror(self) -> "int | bool":
        from emqx_tpu import native
        h = native.intern_mirror_new()
        if h is None:
            self._mirror = False
            return False
        for word, wid in self._to_id.items():
            if not native.intern_mirror_add(h, word, wid):
                native.intern_mirror_free(h)
                self._mirror = False
                return False
        self._mirror = h
        return h

    def mirror_handle(self) -> "int | bool":
        """The native mirror handle (attached lazily), or False."""
        if self._mirror is None:
            return self._attach_mirror()
        return self._mirror

    def intern(self, word: str) -> int:
        """Get-or-assign an id for a filter word."""
        wid = self._to_id.get(word)
        if wid is None:
            wid = len(self._to_word)
            self._to_id[word] = wid
            self._to_word.append(word)
            if isinstance(self._mirror, int):
                from emqx_tpu import native
                if not native.intern_mirror_add(self._mirror, word, wid):
                    native.intern_mirror_free(self._mirror)
                    self._mirror = False
        return wid

    def lookup(self, word: str) -> int:
        """Id for a publish-topic word; UNKNOWN if never seen in a filter."""
        return self._to_id.get(word, UNKNOWN)

    def word(self, wid: int) -> str:
        w = self._to_word[wid]
        if w is None:
            raise KeyError(f"id {wid} has no word")
        return w

    def encode_filter(self, words: list[str]) -> list[int]:
        return [self.intern(w) for w in words]

    def encode_topic(self, words: list[str]) -> list[int]:
        return [self.lookup(w) for w in words]
