"""Topic-level interning: level strings → dense int32 ids.

TPUs cannot branch on strings; every topic level is dictionary-encoded
host-side before it reaches the device (SURVEY.md §7 "Strings on TPU").
Reserved ids:

  PAD (0)      padding beyond a topic's level count
  PLUS (1)     the '+' wildcard word
  HASH (2)     the '#' wildcard word
  UNKNOWN (3)  a publish-topic word that appears in no filter — it can never
               take an exact trie edge, but still matches '+'/'#'

Dynamic ids start at FIRST_DYNAMIC and are assigned on first sight of a word
in a *filter* (publish topics use lookup(), which never allocates).
"""

from __future__ import annotations

import threading

PAD = 0
PLUS = 1
HASH = 2
UNKNOWN = 3
FIRST_DYNAMIC = 4


class InternTable:
    """Host-side word ↔ id map. Route mutations are serialized by the
    router's single-writer update task (the reference serializes them the
    same way via pooled workers, emqx_broker.erl:427-428), but background
    rebuild threads intern() concurrently with the publish-encode path's
    lazy mirror attach, so the mirror state itself is lock-guarded."""

    def __init__(self):
        self._to_id: dict[str, int] = {"+": PLUS, "#": HASH}
        self._to_word: list = [None, "+", "#", None]  # PAD/UNKNOWN unmapped
        # native mirror (SURVEY §7 hard-part 3): word→id replicated into
        # the C library (hash-probed, word bytes confirmed by memcmp —
        # correctness never touches hash uniqueness) so publish batches
        # encode in one native call. None = not yet attached; False =
        # permanently retired (library absent, handles exhausted, or an
        # allocation failure). NOTE: bool is an int subclass, so handle
        # tests must be `type(m) is int`, never isinstance — the retired
        # sentinel False would otherwise coerce to native handle 0, which
        # is some OTHER table's live mirror.
        self._mirror: "int | None | bool" = None
        self._lock = threading.Lock()         # guards _to_id/_to_word tail
        self._attach_lock = threading.Lock()  # serializes attachers
        self._retired: list[int] = []         # parked handles (see below)

    def __len__(self) -> int:
        return len(self._to_word)

    def __del__(self):   # release the C-side handles with the table
        m = getattr(self, "_mirror", None)
        handles = list(getattr(self, "_retired", ()))
        if type(m) is int:
            handles.append(m)
        for h in handles:
            try:
                from emqx_tpu import native
                native.intern_mirror_free(h)
            except Exception:   # noqa: BLE001 — interpreter teardown
                pass

    def _retire_mirror(self, h: int) -> None:
        """DEFERRED free: a concurrent encoder may still hold `h` from
        mirror_handle() inside a native encode call — freeing now would
        be a C-side use-after-free. The handle is parked and released
        with the table (retirement is an allocation-failure path, so the
        parked set stays tiny)."""
        self._retired.append(h)
        # analysis: ok(cross-thread-state) — every caller holds
        # self._lock around this call (see the three call sites); the
        # guard is dynamic, not lexical, so the analyzer can't see it
        self._mirror = False

    def _attach_mirror(self) -> "int | bool":
        """Build the C mirror without stalling concurrent intern()s: copy
        the id-dense word list in lock-free tail chunks, re-snapshotting
        until a pass finds nothing new, then publish the handle under the
        same lock intern() allocates under — a word interned after the
        final snapshot either lands in a later tail pass or sees the
        published handle and adds itself. No word can be lost."""
        from emqx_tpu import native
        with self._attach_lock:
            if self._mirror is not None:      # another attacher won
                return self._mirror
            h = native.intern_mirror_new()
            if h is None:
                with self._lock:
                    self._mirror = False
                return False
            done = FIRST_DYNAMIC
            # seed the reserved words (stable ids, never mutated)
            for word, wid in (("+", PLUS), ("#", HASH)):
                if not native.intern_mirror_add(h, word, wid):
                    with self._lock:
                        self._retire_mirror(h)
                    return False
            while True:
                with self._lock:
                    tail = self._to_word[done:]
                    if not tail:
                        self._mirror = h      # publish: gap-free handoff
                        return h
                base, done = done, done + len(tail)
                for off, word in enumerate(tail):
                    if word is None:
                        continue
                    if not native.intern_mirror_add(h, word, base + off):
                        with self._lock:
                            self._retire_mirror(h)
                        return False

    def mirror_handle(self) -> "int | bool":
        """The native mirror handle (attached lazily), or False."""
        m = self._mirror
        if m is None:
            return self._attach_mirror()
        return m

    def intern(self, word: str) -> int:
        """Get-or-assign an id for a filter word."""
        wid = self._to_id.get(word)
        if wid is not None:
            return wid
        from emqx_tpu import native
        with self._lock:
            wid = self._to_id.get(word)
            if wid is not None:
                return wid
            wid = len(self._to_word)
            self._to_id[word] = wid
            self._to_word.append(word)
            m = self._mirror
            if type(m) is int and \
                    not native.intern_mirror_add(m, word, wid):
                self._retire_mirror(m)
        return wid

    def lookup(self, word: str) -> int:
        """Id for a publish-topic word; UNKNOWN if never seen in a filter."""
        return self._to_id.get(word, UNKNOWN)

    def word(self, wid: int) -> str:
        w = self._to_word[wid]
        if w is None:
            raise KeyError(f"id {wid} has no word")
        return w

    def encode_filter(self, words: list[str]) -> list[int]:
        return [self.intern(w) for w in words]

    def encode_topic(self, words: list[str]) -> list[int]:
        return [self.lookup(w) for w in words]
