"""Pallas TPU kernel: long 1D inclusive prefix sum.

XLA's native lowering of 1D cumsum on this TPU generation is pathological
(measured in round 1: 139ms at 524k elements; ops/scan_ops.py works around
it with lower-triangular matmuls from the host side). This kernel does the
same MXU reformulation *inside one Pallas program*: grid over blocks
(sequential on a TPU core), each step computes its within-block prefix
with one [R,128]x[128,128] lower-triangular matmul + a tiny row-offset
loop, and carries the running total across steps in SMEM scratch — no
cross-block HBM round trips and no host-side stitch.

Used by the shared-subscription rank-over-runs (ops/shared.py) and
benchmarked against both jnp.cumsum and ops.scan_ops.cumsum_blocked.
Exact for values whose running total stays under 2^24 (float32 mantissa);
inputs on this path are 0/1 run-start flags.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

BS = 1024                 # elements per grid step
_R = BS // 128
_LT = np.tril(np.ones((128, 128), np.float32))
# strictly-lower-triangular row mixer: row r picks up all rows < r
# (Mosaic has no cumsum primitive, so cross-row offsets are a matmul too)
_LTR = np.tril(np.ones((_R, _R), np.float32), k=-1)


def _scan_kernel(x_ref, lt_ref, ltr_ref, out_ref, carry_ref):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        carry_ref[0, 0] = jnp.float32(0)

    x = x_ref[:].astype(jnp.float32)              # [R, 128]
    # within-row (128-lane) inclusive prefix on the MXU
    within = jax.lax.dot_general(
        x, lt_ref[:], (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)       # [R, 128]
    # cross-row offsets: sum of all earlier rows, per lane then reduced
    prev_rows = jax.lax.dot_general(
        ltr_ref[:], x, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)       # [R, 128]
    row_off = prev_rows.sum(axis=1, keepdims=True)  # [R, 1]
    carry = carry_ref[0, 0]
    out = within + row_off + carry
    out_ref[:] = out.astype(jnp.int32)
    carry_ref[0, 0] = carry + x.sum()


@functools.partial(jax.jit, static_argnames=("interpret",))
def prefix_sum_pallas(x: jax.Array, *,
                      interpret: bool = None) -> jax.Array:
    """Inclusive prefix sum of a 1D int32 array.

    Exact only while the RUNNING TOTAL stays under 2^24 (float32
    accumulation); the length guard below enforces this for the 0/1-flag
    inputs this path carries — callers with larger element values must
    bound n * max(x) themselves.
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    n = x.shape[0]
    if n > (1 << 24):
        raise ValueError(
            f"prefix_sum_pallas: length {n} exceeds the float32-exact "
            f"bound 2^24")
    nb = max(1, -(-n // BS))
    pad = nb * BS - n
    xb = jnp.pad(x, (0, pad)).reshape(nb * _R, 128)
    out = pl.pallas_call(
        _scan_kernel,
        out_shape=jax.ShapeDtypeStruct((nb * _R, 128), jnp.int32),
        grid=(nb,),
        in_specs=[pl.BlockSpec((_R, 128), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
                  pl.BlockSpec((128, 128), lambda i: (0, 0),
                               memory_space=pltpu.VMEM),
                  pl.BlockSpec((_R, _R), lambda i: (0, 0),
                               memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec((_R, 128), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
        scratch_shapes=[pltpu.SMEM((1, 1), jnp.float32)],
        interpret=interpret,
    )(xb, jnp.asarray(_LT), jnp.asarray(_LTR))
    return out.reshape(-1)[:n]
