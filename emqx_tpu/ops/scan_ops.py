"""Prefix-scan primitives that map onto the MXU.

XLA's associative-scan lowering for long 1D arrays was pathologically slow
on the round-1 libtpu (measured then: jnp.cumsum 139ms,
jnp.maximum.accumulate 1.15s at 524k elements), so long scans are
reformulated as block matmuls against a lower-triangular ones matrix:
prefix-within-block on the MXU (one [nb,BS]x[BS,BS] contraction) plus a
short cross-block cumsum. Re-measured on the current runtime the three
variants (native cumsum, this, ops/pallas_scan.prefix_sum_pallas) are at
parity (~1us/scan at 524k inside a fused loop) — the reformulation is kept
as the default and the bench records the comparison. Exact for values up
to 2^24 per float32 mantissa; inputs here are 0/1 flags and small counts,
far below that.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

BS = 1024
_LT = np.tril(np.ones((BS, BS), np.float32))


@jax.jit
def cumsum_blocked(x: jax.Array) -> jax.Array:
    """Inclusive prefix sum of a 1D int32 array (any length) via MXU blocks."""
    n = x.shape[0]
    # float32 accumulation is exact only up to 2^24; inputs are 0/1 flags so
    # the running sum is bounded by n (static shape → checked at trace time)
    if n > (1 << 24):
        raise ValueError(
            f"cumsum_blocked: length {n} exceeds the float32-exact bound "
            f"2^24; shrink batch*slot_cap or split the scan")
    nb = -(-n // BS)
    pad = nb * BS - n
    xb = jnp.pad(x, (0, pad)).reshape(nb, BS).astype(jnp.float32)
    lt = jnp.asarray(_LT)
    # within[i, j] = sum_{k<=j} xb[i, k]
    within = jax.lax.dot_general(xb, lt, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
    tot = xb.sum(axis=1)
    block_off = jnp.concatenate(
        [jnp.zeros(1, jnp.float32), jnp.cumsum(tot)[:-1]])
    out = (within + block_off[:, None]).astype(x.dtype).reshape(-1)
    return out[:n]
