"""Batched wildcard topic matching on TPU: a level-stepped NFA over TrieTables.

Replaces the reference's per-message recursive trie walk
(emqx_trie.erl:208-266) with one jitted program that matches a whole batch of
publish topics at once:

  - the *batch* is the parallel axis (vectorized over topics),
  - topic *levels* are the time axis, advanced with `lax.scan`,
  - each topic carries a fixed-capacity NFA *frontier* of live trie nodes;
    per level every frontier node expands into its exact-word child (hash
    table probe) and its '+' child, and emits its '#' child's filter,
  - matches are compacted into a fixed [B, match_cap] output with per-topic
    counts; capacity overflow is reported per topic so the host can fall back
    to `HostTrie` for those rare topics (static shapes stay static).

Semantics match emqx_topic.erl match/2 incl. the root-level '$' exclusion
(topics whose first level starts with '$' skip root '+'/'#' branches) and
"sport/# matches sport" ('#' matches zero levels).
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from emqx_tpu.ops.intern import PAD, UNKNOWN
from emqx_tpu.ops.trie import MAX_PROBES, TrieTables, mix_hash


class MatchResult(NamedTuple):
    matches: jax.Array   # [B, match_cap] int32 filter ids, -1 padded
    counts: jax.Array    # [B] int32 true match count (may exceed match_cap)
    overflow: jax.Array  # [B] bool — frontier or match capacity exceeded


def edge_lookup(tables: TrieTables, parent: jax.Array, word: jax.Array) -> jax.Array:
    """Hash-table edge probe: child node id or -1. Shapes broadcast."""
    S = tables.slot_parent.shape[0]
    mask = jnp.uint32(S - 1)
    h = mix_hash(parent, word) & mask
    child = jnp.full(jnp.broadcast_shapes(parent.shape, word.shape), -1, jnp.int32)
    for p in range(MAX_PROBES):
        idx = ((h + np.uint32(p)) & mask).astype(jnp.int32)
        hit = ((parent >= 0) & (tables.slot_parent[idx] == parent)
               & (tables.slot_word[idx] == word))
        child = jnp.where(hit & (child < 0), tables.slot_child[idx], child)
    return child


def _gather_node(arr: jax.Array, idx: jax.Array) -> jax.Array:
    """arr[idx] with -1 indices yielding -1."""
    safe = jnp.clip(idx, 0, arr.shape[0] - 1)
    return jnp.where(idx >= 0, arr[safe], -1)


@functools.partial(jax.jit, static_argnames=("frontier_cap", "match_cap"))
def match_batch(tables: TrieTables, topics: jax.Array, lens: jax.Array,
                is_dollar: jax.Array, *, frontier_cap: int = 16,
                match_cap: int = 64) -> MatchResult:
    """Match a batch of publish topics against the compiled trie.

    topics: [B, L] int32 interned level ids (PAD beyond lens[b]).
    lens: [B] int32 level counts. is_dollar: [B] bool ('$'-rooted topics).
    """
    B, L = topics.shape
    F, M = frontier_cap, match_cap
    rows = jnp.arange(B, dtype=jnp.int32)[:, None]

    # rows with lens == 0 are batch padding: start with an empty frontier
    root0 = jnp.where(lens > 0, 0, -1).astype(jnp.int32)
    frontier0 = jnp.full((B, F), -1, jnp.int32).at[:, 0].set(root0)
    out0 = jnp.full((B, M), -1, jnp.int32)
    count0 = jnp.zeros(B, jnp.int32)
    oflow0 = jnp.zeros(B, bool)

    # scan steps l = 0..L inclusive; word input only consumed while l < len
    words_t = jnp.concatenate(
        [topics.T, jnp.full((1, B), PAD, topics.dtype)], axis=0)
    steps = jnp.arange(L + 1, dtype=jnp.int32)

    def step(carry, xs):
        frontier, out, count, oflow = carry
        l, w = xs
        active = frontier >= 0

        # --- emissions at depth l ---
        hc = _gather_node(tables.hash_child, frontier)
        skip_root_wild = (is_dollar & (l == 0))[:, None]
        hash_fid = _gather_node(tables.node_filter, hc)
        hash_emit = active & (hash_fid >= 0) & ~skip_root_wild
        exact_fid = _gather_node(tables.node_filter, frontier)
        exact_emit = active & (exact_fid >= 0) & (l == lens)[:, None]
        emit_fid = jnp.concatenate([hash_fid, exact_fid], axis=1)
        emit_mask = jnp.concatenate([hash_emit, exact_emit], axis=1)

        pos = count[:, None] + jnp.cumsum(emit_mask, axis=1) - 1
        pos = jnp.where(emit_mask, pos, M)  # out-of-range → dropped
        out = out.at[rows, pos].set(emit_fid, mode="drop")
        count = count + emit_mask.sum(axis=1, dtype=jnp.int32)

        # --- frontier expansion with word w ---
        expanding = active & (l < lens)[:, None]
        parent = jnp.where(expanding, frontier, -1)
        c_exact = edge_lookup(tables, parent, w[:, None])
        c_plus = jnp.where(expanding & ~skip_root_wild,
                           _gather_node(tables.plus_child, frontier), -1)
        cand = jnp.concatenate([c_exact, c_plus], axis=1)  # [B, 2F]
        order = jnp.argsort(cand < 0, axis=1, stable=True)  # valid lanes first
        cand = jnp.take_along_axis(cand, order, axis=1)
        frontier = cand[:, :F]
        oflow = oflow | (cand[:, F:] >= 0).any(axis=1)

        return (frontier, out, count, oflow), None

    (frontier, out, count, oflow), _ = jax.lax.scan(
        step, (frontier0, out0, count0, oflow0), (steps, words_t))

    oflow = oflow | (count > M)
    mr = MatchResult(matches=out, counts=jnp.minimum(count, M),
                     overflow=oflow)
    if tables.cover is not None:
        # subscription covering: the trie held the covering set only —
        # re-expand matched covers into the exact full-set row (fused
        # CSR gather + verify + order-key sort; ops/cover). Trace-time
        # branch: cover-carrying snapshots are a distinct pytree
        # structure, so covering-off programs are byte-identical to
        # before.
        from emqx_tpu.ops.cover import cover_expand
        mr = cover_expand(tables.cover, mr, topics, lens, is_dollar)
    return mr


def merge_match_results(base_matches: jax.Array, base_counts: jax.Array,
                        base_overflow: jax.Array, mr: MatchResult,
                        miss_pos: jax.Array) -> MatchResult:
    """Scatter a miss sub-batch's fresh MatchResult into cached base rows.

    base_*: [U, ...] per-unique-topic rows (cache hits filled by the host,
    everything else garbage-initialized to the empty row). mr: the match
    output for the [Bm] compacted miss lanes. miss_pos: [Bm] destination
    row of each miss lane in the unique array; padding lanes MUST carry
    an out-of-range POSITIVE index (>= U) so mode="drop" discards them —
    a -1 pad would WRAP (jax wraps negative dynamic indices before the
    bounds check) and clobber row U-1 with the empty pad match. The
    match stage is a pure function of the
    immutable table snapshot, so a cached row and a fresh row for the same
    (snapshot, topic) are bit-identical by construction — merging is a
    plain last-writer scatter, no reconciliation needed."""
    return MatchResult(
        matches=base_matches.at[miss_pos].set(mr.matches, mode="drop"),
        counts=base_counts.at[miss_pos].set(mr.counts, mode="drop"),
        overflow=base_overflow.at[miss_pos].set(mr.overflow, mode="drop"))


def encode_topics_str(intern, topics: list, max_levels: int):
    """Encode publish topics from their raw strings — ONE native call
    for the whole batch when the library + mirror are available (split,
    hash, and id-probe per level in C; emqx_tpu/native.py
    topic_encode_batch), else the python per-word path. Same outputs as
    encode_topics: (ids [B,L], lens [B], is_dollar [B], too_long [B])."""
    h = intern.mirror_handle()
    if h is not False:
        from emqx_tpu import native
        out = native.topic_encode_batch(h, topics, max_levels,
                                        UNKNOWN, PAD)
        if out is not None:
            return out
    from emqx_tpu.utils.topic import tokens
    # NOT pre-truncated: encode_topics must see the real level count so
    # deeper-than-L topics get the too_long host-fallback flag (a
    # truncated topic could falsely match a filter on its prefix)
    return encode_topics(intern, [tokens(t) for t in topics], max_levels)


def encode_topics(intern, topic_words: list, max_levels: int):
    """Host helper: list of word-lists → (topics [B,L], lens [B], is_dollar [B]).

    Topics longer than max_levels are truncated and flagged via the returned
    `too_long` mask — the caller must route those to the host fallback.
    """
    B = len(topic_words)
    L = max_levels
    topics = np.full((B, L), PAD, np.int32)
    lens = np.zeros(B, np.int32)
    dollar = np.zeros(B, bool)
    too_long = np.zeros(B, bool)
    for i, ws in enumerate(topic_words):
        n = len(ws)
        if n > L:
            too_long[i] = True
            n = L
        lens[i] = n
        dollar[i] = ws[0].startswith("$") if ws else False
        topics[i, :n] = [intern.lookup(w) for w in ws[:n]]
    return topics, lens, dollar, too_long
