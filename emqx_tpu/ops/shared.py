"""Shared-subscription member selection on device.

The reference picks one group member per message with pluggable strategies
(emqx_shared_sub.erl:239-290 — random, round_robin, sticky, hash_clientid,
hash_topic; round_robin keeps a per-group counter in the worker's process
dictionary). Here selection is *batched and deterministic*: each (group,
filter) pair is a dense "shared slot" with a persistent cursor; for a batch
of messages, every occurrence of a slot gets successive cursor offsets in
batch order (an associative rank-over-equal-slots computed by sort — SURVEY
§7 hard-part 4), so round-robin semantics hold within and across batches
with no sequential loop.

Strategies round_robin / random / hash_* map onto the same primitive by
choosing the base offset (cursor, message hash) — see pick_members.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp

from emqx_tpu.ops.fanout import SubTable

STRATEGY_ROUND_ROBIN = 0
STRATEGY_RANDOM = 1       # pseudo-random: hash of (msg seed, slot)
STRATEGY_HASH_TOPIC = 2   # stable per topic-hash
STRATEGY_HASH_CLIENT = 3  # stable per publisher-hash
STRATEGY_STICKY = 4       # persistent per-slot member (cursor = affinity)
STRATEGIES = {
    "round_robin": STRATEGY_ROUND_ROBIN,
    "random": STRATEGY_RANDOM,
    "hash_topic": STRATEGY_HASH_TOPIC,
    "hash_clientid": STRATEGY_HASH_CLIENT,
    # sticky rides the SAME cursor state as round_robin, reinterpreted:
    # the host seeds each slot's cursor with its sticky member's index
    # (device_engine.capture_shared) and the kernel never advances it —
    # every message in every batch picks cursor % size, so affinity
    # holds within and across batches with zero feedback from the
    # device. Re-picks (member death/unsubscribe) are feedback-dependent
    # and stay host-side: the consume fallback picks a new member, the
    # host record updates, and the next snapshot re-seeds the cursor
    # (reference: emqx_shared_sub.erl:269-283).
    "sticky": STRATEGY_STICKY,
}


class SharedPickResult(NamedTuple):
    rows: jax.Array         # [B, K] picked member session row, -1 pad
    opts: jax.Array         # [B, K] packed subopts of picked member
    new_cursors: jax.Array  # [G] updated round-robin cursors
    occur: jax.Array        # [G] occurrences of each slot in this batch
                            # (lets a data-parallel caller psum across shards
                            # and rebase cursors consistently)


# block width of the sort-free rank scan: larger blocks mean fewer
# sequential scan steps but a quadratically larger [L, L] in-block
# compare — sweepable on hardware via env (profile_step shows the
# rank/occur stage cost directly)
import os as _os


def resolve_rank_block(configured=None) -> int:
    """The one rank-block resolution: an explicit width (callers use
    ``set_rank_block``) beats ``EMQX_TPU_RANK_BLOCK`` beats 512.
    Import-time knob — config cannot reach module import, so the env is
    the deploy-time sweep handle; must be an integer >= 8 (a narrower
    block degenerates the in-block compare), anything else fails
    loudly."""
    raw = configured if configured is not None \
        else _os.environ.get("EMQX_TPU_RANK_BLOCK", 512)
    try:
        block = int(raw)
    except (TypeError, ValueError) as _e:
        raise ValueError(
            f"EMQX_TPU_RANK_BLOCK must be an integer, got "
            f"{raw!r}") from _e
    if block < 8:
        raise ValueError(
            f"EMQX_TPU_RANK_BLOCK must be >= 8, got {block}")
    return block


_RANK_BLOCK = resolve_rank_block()


def set_rank_block(width: int) -> None:
    """Set the default block width for subsequently TRACED programs
    (bench.py self-tunes this on the target hardware before tracing its
    main step — the optimum is hardware-specific: CPU lowers the [L, L]
    compare to scalar loops and wants small blocks, the TPU VPU wants
    fewer scan steps). Already-jitted programs keep their width."""
    global _RANK_BLOCK
    if width < 8:
        raise ValueError(f"rank block width must be >= 8, got {width}")
    _RANK_BLOCK = width


def _rank_and_occur_blocked(sids: jax.Array, n_slots: int,
                            block: int | None = None):
    """Sort-free rank/occur for TPU (round-3): the round-2 argsort of the
    whole flattened batch measured as the fused step's dominant cost
    (~2/3 of the batch time; TPU sorts are bitonic-network expensive).
    The flat array is scanned in `block`-wide blocks (default
    _RANK_BLOCK; static — a sweep jits one program per width): within a
    block, rank is a strictly-lower-triangular equality reduction (one
    [L, L] compare + masked row-sum on the VPU — the associative
    formulation of SURVEY §7 hard-part 4); across blocks a per-slot
    count table is carried, gathered for the block's base and advanced
    with a unique-index scatter at each slot's LAST in-block occurrence.
    The carried table's final state IS `occur`.
    """
    B, K = sids.shape
    flat = sids.reshape(-1)
    n = flat.shape[0]
    L = _RANK_BLOCK if block is None else block
    if L < 8:
        raise ValueError(f"rank block width must be >= 8, got {L}")
    nb = -(-n // L)
    pad = nb * L - n
    blocks = jnp.pad(flat, (0, pad), constant_values=-1).reshape(nb, L)

    def step(carry, s):
        valid = s >= 0
        safe = jnp.where(valid, s, 0)
        base = jnp.where(valid, carry[safe], 0)           # [L] gather
        eq = (s[:, None] == s[None, :]) & valid[:, None]  # [L, L]
        idx = jax.lax.broadcasted_iota(jnp.int32, (L, L), 0)
        jdx = jax.lax.broadcasted_iota(jnp.int32, (L, L), 1)
        rank_in = (eq & (jdx < idx)).sum(axis=1, dtype=jnp.int32)
        is_last = ~(eq & (jdx > idx)).any(axis=1)
        carry = carry.at[
            jnp.where(valid & is_last, s, jnp.int32(n_slots))
        ].add(rank_in + 1, mode="drop", unique_indices=True)
        return carry, base + rank_in

    occur, rank_blocks = jax.lax.scan(
        step, jnp.zeros(n_slots, jnp.int32), blocks)
    rank = rank_blocks.reshape(-1)[:n]
    return rank.reshape(B, K), occur


def _rank_and_occur_sorted(sids: jax.Array, n_slots: int):
    """Sort-based rank/occur (the XLA-CPU winner: its sort is fast and
    the [L, L] block reduction lowers to scalar loops there). Every
    scatter has provably unique live indices; `occur` derives from run
    ends instead of a non-unique scatter-add."""
    from emqx_tpu.ops.scan_ops import cumsum_blocked

    B, K = sids.shape
    flat = sids.reshape(-1)
    n = flat.shape[0]
    order = jnp.argsort(flat, stable=True)
    sorted_sids = flat[order]
    is_start = jnp.concatenate(
        [jnp.ones(1, bool), sorted_sids[1:] != sorted_sids[:-1]])
    is_end = jnp.concatenate(
        [sorted_sids[1:] != sorted_sids[:-1], jnp.ones(1, bool)])
    pos = jnp.arange(n, dtype=jnp.int32)
    run_id = cumsum_blocked(is_start.astype(jnp.int32)) - 1
    starts = jnp.zeros(n, jnp.int32).at[
        jnp.where(is_start, run_id, n)].set(pos, mode="drop",
                                            unique_indices=True)
    rank_sorted = pos - starts[run_id]
    rank = jnp.zeros(n, jnp.int32).at[order].set(rank_sorted,
                                                 unique_indices=True)
    # occur: at each run END the rank is (count-1); one unique scatter
    occur = jnp.zeros(n_slots, jnp.int32).at[
        jnp.where(is_end & (sorted_sids >= 0), sorted_sids, n_slots)
    ].set(rank_sorted + 1, mode="drop", unique_indices=True)
    return rank.reshape(B, K), occur


def _rank_and_occur(sids: jax.Array, n_slots: int):
    """rank[b,k] = #occurrences of sids[b,k] earlier in flattened batch
    order; occur[g] = occurrences of slot g in the batch. -1 entries get
    rank 0 (unused). Backend-selected implementation (identical results;
    oracle-tested): blockwise equality reduction on accelerators, sort
    on CPU."""
    import jax as _jax
    if _jax.default_backend() == "cpu":
        return _rank_and_occur_sorted(sids, n_slots)
    return _rank_and_occur_blocked(sids, n_slots)


@functools.partial(jax.jit, static_argnames=())
def pick_members(table: SubTable, cursors: jax.Array, sids: jax.Array,
                 strategy: jax.Array, msg_hash: jax.Array) -> SharedPickResult:
    """Pick one member per matched shared slot, batched.

    cursors: [G] persistent per-slot round-robin counters (device state).
    sids: [B, K] matched shared-slot ids (-1 pad) from shared_slots().
    strategy: scalar int32 (STRATEGY_*).
    msg_hash: [B] int32 per-message hash (topic/publisher hash or seed),
      used by random/hash strategies.
    """
    B, K = sids.shape
    valid = sids >= 0
    safe = jnp.clip(sids, 0)
    lo = table.shared_start[safe]
    size = table.shared_start[safe + 1] - lo  # [B, K] members per slot
    nonempty = valid & (size > 0)

    rank, occur = _rank_and_occur(sids, cursors.shape[0])
    base_rr = cursors[safe] + rank
    base_hash = (msg_hash[:, None].astype(jnp.uint32)
                 * jnp.uint32(0x9E3779B1) ^ safe.astype(jnp.uint32)).astype(jnp.int32)
    base = jnp.where(strategy == STRATEGY_ROUND_ROBIN, base_rr,
                     jnp.where(strategy == STRATEGY_STICKY,
                               cursors[safe],      # affinity, no rank
                               jnp.abs(base_hash)))
    member = jnp.where(nonempty, base % jnp.maximum(size, 1), 0)
    idx = lo + member
    rows = jnp.where(nonempty, table.shared_row[jnp.clip(idx, 0)], -1)
    opts = jnp.where(nonempty, table.shared_opts[jnp.clip(idx, 0)],
                     jnp.zeros((), table.shared_opts.dtype))

    # advance cursors by per-slot occurrence counts (round_robin only)
    new_cursors = jnp.where(strategy == STRATEGY_ROUND_ROBIN,
                            cursors + occur.astype(cursors.dtype), cursors)
    return SharedPickResult(rows=rows, opts=opts, new_cursors=new_cursors,
                            occur=occur.astype(cursors.dtype))
