"""Device-side PUBLISH fan-out: matched filters → subscriber delivery rows.

Replaces the reference's per-message fold over ETS subscriber bags
(emqx_broker.erl dispatch/2 :282-308, incl. the >1024-subscriber shard
special-case in emqx_broker_helper.erl) with a batched CSR segment-gather:
subscribers live in one columnar table (filter-id → contiguous row range);
fan-out for a whole topic batch is a vmapped searchsorted over per-topic
segment offsets. No shard special-case is needed — capacity is explicit and
overflow topics fall back to the host CSR (numpy) path.

Outputs are *session rows* (int32 indices into the host session registry) +
packed subscription options, not pids: the host delivers to sockets.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np


class SubTable(NamedTuple):
    """Columnar subscriber store, a JAX pytree.

    sub_start: [F+1] CSR offsets per filter id (F = filter capacity).
    sub_row:   [S] session row per subscription entry.
    sub_opts:  [S] packed subopts: qos | nl<<2 | rap<<3 | rh<<4 (SubOpts.to_byte).
    fs_start:  [F+1] CSR offsets: filter id → shared-slot list.
    fs_slot:   [FS] shared-slot ids ((group, filter) pairs get dense slot ids).
    shared_start: [G+1] CSR offsets: shared slot → member list.
    shared_row:   [SM] session row per shared member.
    shared_opts:  [SM] packed subopts per shared member.
    """

    sub_start: jax.Array
    sub_row: jax.Array
    sub_opts: jax.Array           # int8: packed subopts fit 6 bits

    fs_start: jax.Array
    fs_slot: jax.Array
    shared_start: jax.Array
    shared_row: jax.Array
    shared_opts: jax.Array


class FanoutResult(NamedTuple):
    rows: jax.Array      # [B, D] session rows, -1 padded
    opts: jax.Array      # [B, D] packed subopts
    counts: jax.Array    # [B] true delivery count (may exceed D)
    overflow: jax.Array  # [B] bool


def _segment_expand(starts: jax.Array, values: jax.Array, seg_ids: jax.Array,
                    cap: int):
    """Expand CSR segments selected per batch row into fixed-width outputs.

    starts: [F+1] CSR. values: [S]. seg_ids: [B, M] segment (filter) ids, -1
    padded. Returns (out [B, cap] gathered values (-1 pad), idx [B, cap] flat
    indices into `values` (-1 pad), counts [B], overflow [B]).
    """
    B, M = seg_ids.shape
    valid = seg_ids >= 0
    safe = jnp.clip(seg_ids, 0, starts.shape[0] - 2)
    seg_lo = jnp.where(valid, starts[safe], 0)
    seg_len = jnp.where(valid, starts[safe + 1] - seg_lo, 0)  # [B, M]
    # exclusive prefix of segment lengths per row → output offsets
    ends = jnp.cumsum(seg_len, axis=1)            # [B, M] inclusive
    offs = ends - seg_len                         # [B, M] exclusive
    total = ends[:, -1]
    # for each output slot d: which segment covers it?
    d = jnp.arange(cap, dtype=jnp.int32)
    # searchsorted per row over the inclusive ends: first segment with end > d
    seg_of = jax.vmap(lambda e: jnp.searchsorted(e, d, side="right"))(ends)
    seg_of = jnp.minimum(seg_of, M - 1)
    in_range = d[None, :] < total[:, None]
    lo = jnp.take_along_axis(seg_lo, seg_of, axis=1)
    off = jnp.take_along_axis(offs, seg_of, axis=1)
    idx = lo + (d[None, :] - off)
    idx = jnp.where(in_range, idx, -1)
    out = jnp.where(in_range, values[jnp.clip(idx, 0)], -1)
    return out, idx, total.astype(jnp.int32), total > cap


@functools.partial(jax.jit, static_argnames=("fanout_cap",))
def fanout_normal(table: SubTable, matches: jax.Array, *,
                  fanout_cap: int = 128) -> FanoutResult:
    """Gather normal (non-shared) subscriber rows for matched filters.

    matches: [B, M] matched filter ids from match_batch, -1 padded.
    """
    rows, idx, counts, overflow = _segment_expand(
        table.sub_start, table.sub_row, matches, fanout_cap)
    opts = jnp.where(idx >= 0, table.sub_opts[jnp.clip(idx, 0)],
                     jnp.int8(0))
    return FanoutResult(rows=rows, opts=opts, counts=counts, overflow=overflow)


def _csr(n_segs: int, seg_map: dict, cap_rows: int):
    """dict seg→list[(a, b)] → (starts [n_segs+1], a[], b[]) padded to cap."""
    starts = np.zeros(n_segs + 1, np.int32)
    for s, entries in seg_map.items():
        starts[s + 1] = len(entries)
    np.cumsum(starts, out=starts)
    total = int(starts[-1])
    cap = max(cap_rows, total, 1)
    a = np.full(cap, -1, np.int32)
    b = np.zeros(cap, np.int32)
    for s, entries in seg_map.items():
        lo = starts[s]
        for i, (x, y) in enumerate(entries):
            a[lo + i] = x
            b[lo + i] = y
    return starts, a, b


def build_subtable(filter_cap: int,
                   normal: dict,
                   filter_slots: dict,
                   shared_members: dict,
                   slot_cap: int = 1,
                   sub_rows_cap: int = 1,
                   fs_rows_cap: int = 1,
                   member_rows_cap: int = 1) -> SubTable:
    """Host builder: python dicts → columnar SubTable (numpy arrays).

    normal: filter id → list[(session_row, packed_opts)].
    filter_slots: filter id → list[shared_slot_id].
    shared_members: shared_slot_id → list[(session_row, packed_opts)].

    The *_cap arguments set minimum array capacities so that independently
    built shards stack to one leading-axis array (parallel.sharded) and jit
    shapes stay stable across rebuilds.
    """
    sub_start, sub_row, sub_opts = _csr(filter_cap, normal, sub_rows_cap)
    fs_map = {f: [(s, 0) for s in slots] for f, slots in filter_slots.items()}
    fs_start, fs_slot, _ = _csr(filter_cap, fs_map, fs_rows_cap)
    n_slots = max(slot_cap, 1 + max(shared_members.keys(), default=-1),
                  1 + int(fs_slot.max(initial=-1)))
    shared_start, shared_row, shared_opts = _csr(n_slots, shared_members,
                                                 member_rows_cap)
    # packed subopts fit 6 bits: an int8 plane quarters the HBM traffic of
    # the opts gathers + outputs (round-2 VERDICT perf item)
    sub_opts = sub_opts.astype(np.int8)
    shared_opts = shared_opts.astype(np.int8)
    return SubTable(sub_start=sub_start, sub_row=sub_row, sub_opts=sub_opts,
                    fs_start=fs_start, fs_slot=fs_slot,
                    shared_start=shared_start, shared_row=shared_row,
                    shared_opts=shared_opts)


@functools.partial(jax.jit, static_argnames=("slot_cap",))
def shared_slots(table: SubTable, matches: jax.Array, *,
                 slot_cap: int = 16):
    """Expand matched filters into shared-subscription slot ids.

    Returns (sids [B, slot_cap] shared-slot ids (-1 pad), overflow [B]).
    """
    sids, _idx, _counts, overflow = _segment_expand(
        table.fs_start, table.fs_slot, matches, slot_cap)
    return sids, overflow
