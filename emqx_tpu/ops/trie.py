"""Columnar topic-filter trie: batch compiler (host, numpy) + host fallback trie.

The reference stores the wildcard-filter trie as mnesia ordered_set keys walked
recursively per message (emqx_trie.erl:45-51,208-266). Here the trie is
*compiled*: the full filter set is lexicographically sorted and collapsed into
flat arrays in one vectorized pass, producing:

  - an open-addressing hash table of exact edges  (parent_node, word) → child
  - per-node '+' and '#' child slots (wildcard branches of the match NFA)
  - per-node terminal filter id

These arrays are what `emqx_tpu.ops.match` walks on device, batched over
topics. Mutation model (SURVEY.md §7 hard-part 1): the subscription set is the
durable truth; tables are soft state — deltas accumulate in a `HostTrie` and
the columnar tables are rebuilt/double-buffered, with pow2 capacity padding so
jit shapes stay stable across rebuilds.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import numpy as np

from emqx_tpu.ops.intern import HASH, PAD, PLUS

# Linear-probe budget for the edge hash table. The builder grows capacity
# until every edge lands within MAX_PROBES of its home slot, so the device
# lookup can unroll exactly this many probes.
MAX_PROBES = 8


class TrieTables(NamedTuple):
    """Flat device-ready trie. All arrays int32; a clean JAX pytree.

    slot_parent/slot_word/slot_child: edge hash table, -1 parent = empty slot.
    plus_child/hash_child: wildcard branch per node, -1 = none.
    node_filter: terminal filter id per node, -1 = none.
    num_nodes/num_edges: scalars (informational; capacities come from shapes).
    cover: optional subscription-covering expansion state (ops/cover):
      when present the trie holds the COVERING set only and match_batch
      re-expands matched covers into the exact full-set result. None is
      an empty pytree node, so existing snapshots are unaffected.
    """

    slot_parent: np.ndarray  # [S]
    slot_word: np.ndarray    # [S]
    slot_child: np.ndarray   # [S]
    plus_child: np.ndarray   # [N]
    hash_child: np.ndarray   # [N]
    node_filter: np.ndarray  # [N]
    num_nodes: np.ndarray    # []
    num_edges: np.ndarray    # []
    cover: Optional[NamedTuple] = None  # CoverTables (ops/cover)


def mix_hash(parent, word):
    """32-bit hash of an edge key; identical math under numpy and jax.numpy."""
    p = parent.astype("uint32")
    w = word.astype("uint32")
    h = (p * np.uint32(0x9E3779B1)) ^ (w * np.uint32(0x85EBCA77))
    h = h ^ (h >> np.uint32(16))
    h = h * np.uint32(0x7FEB352D)
    h = h ^ (h >> np.uint32(15))
    return h


def _next_pow2(x: int) -> int:
    return 1 << max(4, (x - 1).bit_length())


def _build_edge_table(parents: np.ndarray, words_: np.ndarray,
                      children: np.ndarray, capacity: int):
    """Vectorized linear-probe insertion; returns None if MAX_PROBES exceeded."""
    mask = capacity - 1
    slot_parent = np.full(capacity, -1, np.int32)
    slot_word = np.zeros(capacity, np.int32)
    slot_child = np.full(capacity, -1, np.int32)
    home = (mix_hash(parents, words_) & np.uint32(mask)).astype(np.int64)
    pending = np.arange(len(parents))
    probe = np.zeros(len(parents), np.int64)
    while len(pending):
        if probe.max(initial=0) >= MAX_PROBES:
            return None
        target = (home[pending] + probe) & mask
        free = slot_parent[target] == -1
        # among pending edges probing a free slot, first claimant per slot wins
        tgt_free = np.where(free, target, -1)
        _, winner_idx = np.unique(tgt_free, return_index=True)
        winner_idx = winner_idx[tgt_free[winner_idx] >= 0]
        win = np.zeros(len(pending), bool)
        win[winner_idx] = True
        placed = pending[win]
        slot_parent[target[win]] = parents[placed]
        slot_word[target[win]] = words_[placed]
        slot_child[target[win]] = children[placed]
        pending = pending[~win]
        probe = probe[~win] + 1
    return slot_parent, slot_word, slot_child


def build_tables(words: np.ndarray, lens: np.ndarray,
                 filter_ids: Optional[np.ndarray] = None,
                 node_capacity: Optional[int] = None,
                 slot_capacity: Optional[int] = None) -> TrieTables:
    """Compile a deduplicated filter set into TrieTables.

    words: [F, L] int32 interned level ids, PAD beyond lens[f].
    lens:  [F] level counts (>=1).
    filter_ids: [F] dense filter ids (default: row index).

    One vectorized pass per level: rows are lexsorted so equal prefixes are
    contiguous; new trie nodes are boundaries of (parent, word) runs.
    """
    words = np.asarray(words, np.int32)
    lens = np.asarray(lens, np.int64)
    F, L = words.shape if words.ndim == 2 else (0, 0)
    if filter_ids is None:
        filter_ids = np.arange(F)
    filter_ids = np.asarray(filter_ids, np.int64)

    if F == 0:
        return _assemble(np.array([-1]), np.array([0]), np.array([-1]),
                         1, 0, node_capacity, slot_capacity)

    order = np.lexsort(tuple(words[:, l] for l in range(L - 1, -1, -1)))
    Ws = words[order]
    ls = lens[order]
    fids = filter_ids[order]

    parent = np.zeros(F, np.int64)  # node id after consuming l words (root=0)
    num_nodes = 1
    node_parents = [np.array([-1], np.int64)]
    node_words = [np.array([PAD], np.int64)]
    node_filters = [np.array([-1], np.int64)]

    for l in range(L):
        alive = ls > l
        if not alive.any():
            break
        w = Ws[:, l].astype(np.int64)
        prev_alive = np.concatenate(([False], alive[:-1]))
        prev_parent = np.concatenate(([-2], parent[:-1]))
        prev_w = np.concatenate(([-2], w[:-1]))
        is_new = alive & (~prev_alive | (parent != prev_parent) | (w != prev_w))
        rank = np.cumsum(is_new) - 1  # per-row index of its (parent,word) run
        node_of_row = num_nodes + rank
        cnt = int(is_new.sum())

        node_parents.append(parent[is_new])
        node_words.append(w[is_new])
        nf = np.full(cnt, -1, np.int64)
        term = alive & (ls == l + 1)
        tnodes = node_of_row[term] - num_nodes
        if len(np.unique(tnodes)) != len(tnodes):
            raise ValueError("duplicate filters passed to build_tables")
        nf[tnodes] = fids[term]
        node_filters.append(nf)

        parent = np.where(alive, node_of_row, parent)
        num_nodes += cnt

    node_parent = np.concatenate(node_parents)
    node_word = np.concatenate(node_words)
    node_filter = np.concatenate(node_filters)
    return _assemble(node_parent, node_word, node_filter, num_nodes,
                     F, node_capacity, slot_capacity)


def _assemble(node_parent, node_word, node_filter, num_nodes, num_filters,
              node_capacity, slot_capacity) -> TrieTables:
    ids = np.arange(num_nodes)
    N = node_capacity or _next_pow2(num_nodes)
    if N < num_nodes:
        raise ValueError(f"node_capacity {N} < {num_nodes} nodes")

    plus_child = np.full(N, -1, np.int32)
    hash_child = np.full(N, -1, np.int32)
    nf = np.full(N, -1, np.int32)
    nf[:num_nodes] = node_filter

    is_plus = (node_word == PLUS) & (ids != 0)
    is_hash = (node_word == HASH) & (ids != 0)
    plus_child[node_parent[is_plus]] = ids[is_plus]
    hash_child[node_parent[is_hash]] = ids[is_hash]

    em = ~is_plus & ~is_hash & (ids != 0)
    eparents = node_parent[em].astype(np.int32)
    ewords = node_word[em].astype(np.int32)
    echildren = ids[em].astype(np.int32)
    num_edges = len(eparents)

    S = slot_capacity or _next_pow2(max(16, 2 * num_edges))
    while True:
        built = _build_edge_table(eparents, ewords, echildren, S)
        if built is not None:
            break
        S *= 2
    slot_parent, slot_word, slot_child = built

    return TrieTables(
        slot_parent=slot_parent, slot_word=slot_word, slot_child=slot_child,
        plus_child=plus_child, hash_child=hash_child, node_filter=nf,
        num_nodes=np.int32(num_nodes), num_edges=np.int32(num_edges),
    )


class HostTrie:
    """Dynamic dict-based trie over interned word ids.

    Role: (a) accumulator for subscribe/unsubscribe deltas between columnar
    rebuilds, (b) CPU fallback matcher for topics that overflow the device
    NFA's static frontier/match/level capacities. Same match semantics as the
    device NFA and the reference (emqx_trie.erl do_match + root-'$' rule).
    """

    __slots__ = ("children", "plus", "hash", "filter_id")

    def __init__(self):
        self.children: dict[int, HostTrie] = {}
        self.plus: Optional[HostTrie] = None
        self.hash: Optional[HostTrie] = None
        self.filter_id: int = -1

    def insert(self, word_ids: list[int], filter_id: int) -> None:
        node = self
        for w in word_ids:
            if w == PLUS:
                node.plus = node.plus or HostTrie()
                node = node.plus
            elif w == HASH:
                node.hash = node.hash or HostTrie()
                node = node.hash
            else:
                nxt = node.children.get(w)
                if nxt is None:
                    nxt = node.children[w] = HostTrie()
                node = nxt
        node.filter_id = filter_id

    def delete(self, word_ids: list[int]) -> None:
        path = [(None, self)]
        node = self
        for w in word_ids:
            nxt = (node.plus if w == PLUS else
                   node.hash if w == HASH else node.children.get(w))
            if nxt is None:
                return
            path.append((w, nxt))
            node = nxt
        node.filter_id = -1
        # prune empty tails
        for i in range(len(path) - 1, 0, -1):
            w, n = path[i]
            if n.filter_id == -1 and not n.children and n.plus is None and n.hash is None:
                pnode = path[i - 1][1]
                if w == PLUS:
                    pnode.plus = None
                elif w == HASH:
                    pnode.hash = None
                else:
                    pnode.children.pop(w, None)
            else:
                break

    def match(self, word_ids: list[int], is_dollar: bool = False) -> list[int]:
        """Matching filter ids for a (non-wildcard) topic."""
        out: list[int] = []
        self._match(word_ids, 0, is_dollar, out)
        return out

    def _match(self, ws: list[int], i: int, dollar_root: bool, out: list[int]) -> None:
        skip_wild = dollar_root and i == 0
        if not skip_wild and self.hash is not None and self.hash.filter_id >= 0:
            out.append(self.hash.filter_id)
        if i == len(ws):
            if self.filter_id >= 0:
                out.append(self.filter_id)
            return
        if not skip_wild and self.plus is not None:
            self.plus._match(ws, i + 1, dollar_root, out)
        nxt = self.children.get(ws[i])
        if nxt is not None:
            nxt._match(ws, i + 1, dollar_root, out)

    def is_empty(self) -> bool:
        return self.filter_id < 0 and not self.children and self.plus is None and self.hash is None
