"""emqx_tpu — a TPU-native distributed MQTT messaging framework.

Brand-new framework with the capabilities of the reference EMQ X broker
(/root/reference): MQTT 3.1/3.1.1/5.0 pub/sub with +/# wildcard routing,
shared subscriptions, QoS 0/1/2 sessions, retained/delayed messages, hooks,
rule engine, authn/authz, clustering, management — with the wildcard
topic-match + fan-out hot path executed as a batched NFA over a columnar
HBM-resident trie on TPU (JAX/XLA/Pallas), instead of the reference's
per-message ETS/mnesia trie walks (emqx_trie.erl:208-266).

Package layout:
  utils/     topic algebra, ids, metrics, small pure helpers
  mqtt/      MQTT v3.1.1/v5 wire codec and packet model (emqx_frame.erl)
  ops/       device-side ops: interning, columnar trie, batched match,
             fan-out gather, shared-sub selection (emqx_trie/emqx_broker)
  parallel/  mesh + shard_map sharded matching, multi-host plumbing
  models/    the flagship jittable "route engine" step combining the ops
  broker/    host runtime: listeners, connections, channel FSM, sessions,
             connection manager, hooks, pubsub engine (emqx_broker.erl)
"""

from emqx_tpu.version import __version__  # noqa: F401
