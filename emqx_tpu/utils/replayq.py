"""Disk-backed replay queue.

Parity: the replayq dep used by emqx_bridge_mqtt
(emqx_bridge_worker.erl:142-143,211-217) — messages appended to segment
files survive restarts; consumers pop batches and ack, which advances a
durable commit marker; unacked items are replayed after a crash. A `dir` of
None gives a pure in-memory queue (replayq's mem-only mode).

Layout: <dir>/<segno>.q files of length-prefixed items; <dir>/COMMIT holds
"segno offset" of the first unacked item.
"""

from __future__ import annotations

import os
import struct
from typing import Optional

DEFAULT_SEG_BYTES = 10 << 20


class ReplayQ:
    def __init__(self, dir: Optional[str] = None,
                 seg_bytes: int = DEFAULT_SEG_BYTES,
                 fsync: bool = True):
        self.dir = dir
        self.seg_bytes = seg_bytes
        self.fsync = fsync
        self._mem: list[bytes] = []
        # reader position: (segno, item offset within segment)
        self._rseg = 0
        self._roff = 0
        self._wseg = 0
        self._count = 0                     # live (unacked) items
        self._cache_seg: Optional[int] = None   # parsed-segment read cache
        self._cache_items: list[bytes] = []
        if dir is not None:
            os.makedirs(dir, exist_ok=True)
            self._recover()

    # ---- disk helpers ----
    def _seg_path(self, segno: int) -> str:
        return os.path.join(self.dir, f"{segno:010d}.q")

    def _commit_path(self) -> str:
        return os.path.join(self.dir, "COMMIT")

    def _recover(self) -> None:
        segs = sorted(int(f[:-2]) for f in os.listdir(self.dir)
                      if f.endswith(".q"))
        self._wseg = segs[-1] if segs else 0
        try:
            with open(self._commit_path()) as f:
                seg, off = f.read().split()
                self._rseg, self._roff = int(seg), int(off)
        except (FileNotFoundError, ValueError):
            self._rseg = segs[0] if segs else 0
            self._roff = 0
        # drop fully-acked segments
        for s in segs:
            if s < self._rseg:
                os.unlink(self._seg_path(s))
        self._count = self._scan_count()

    def _scan_count(self) -> int:
        total = 0
        seg, off = self._rseg, self._roff
        while seg <= self._wseg:
            total += max(0, len(self._read_seg(seg)) - off)
            seg += 1
            off = 0
        return total

    def _read_seg(self, segno: int) -> list[bytes]:
        try:
            with open(self._seg_path(segno), "rb") as f:
                data = f.read()
        except FileNotFoundError:
            return []
        from emqx_tpu import native
        # torn tail writes are discarded by the scan; loop so dense
        # segments beyond one scan's max_items are never truncated
        items: list[bytes] = []
        base = 0
        while base < len(data):
            spans = native.replayq_scan(data[base:])
            if not spans:
                break
            items.extend(data[base + o:base + o + n] for o, n in spans)
            base += spans[-1][0] + spans[-1][1]
        return items

    # ---- queue api ----
    def append(self, item: bytes) -> None:
        self._count += 1
        if self.dir is None:
            self._mem.append(item)
            return
        if self._wseg < self._rseg:
            # a full drain advanced the reader past the old write segment;
            # never write behind the read pointer or items become invisible
            self._wseg = self._rseg
        path = self._seg_path(self._wseg)
        if (os.path.exists(path)
                and os.path.getsize(path) >= self.seg_bytes):
            self._wseg += 1
            path = self._seg_path(self._wseg)
        with open(path, "ab") as f:
            f.write(struct.pack(">I", len(item)) + item)
            f.flush()
            if self.fsync:
                os.fsync(f.fileno())
        self._cache_seg = None   # invalidate read cache

    def pop(self, n: int = 1) -> tuple[list[bytes], Optional[tuple]]:
        """Return up to n items and an ack ref (None when empty)."""
        if self.dir is None:
            items = self._mem[:n]
            return items, ("mem", len(items)) if items else None
        items: list[bytes] = []
        seg, off = self._rseg, self._roff
        while len(items) < n and seg <= self._wseg:
            seg_items = self._seg_items_cached(seg)
            take = seg_items[off:off + (n - len(items))]
            items.extend(take)
            off += len(take)
            if off >= len(seg_items):
                seg += 1
                off = 0
        if not items:
            return [], None
        return items, (seg, off, len(items))

    def _seg_items_cached(self, seg: int) -> list[bytes]:
        if self._cache_seg != seg:
            self._cache_items = self._read_seg(seg)
            self._cache_seg = seg
        return self._cache_items

    def ack(self, ref: tuple) -> None:
        if self.dir is None:
            acked = ref[1]
            self._mem = self._mem[acked:]
            self._count = len(self._mem)
            return
        seg, off, n_items = ref
        with open(self._commit_path(), "w") as f:
            f.write(f"{seg} {off}")
            f.flush()
            os.fsync(f.fileno())
        for s in range(self._rseg, seg):
            try:
                os.unlink(self._seg_path(s))
            except FileNotFoundError:
                pass
        self._rseg, self._roff = seg, off
        # decrement by the popped batch — a full rescan here would make
        # every ack O(backlog bytes)
        self._count = max(0, self._count - n_items)

    def count(self) -> int:
        return self._count if self.dir is not None else len(self._mem)

    def is_empty(self) -> bool:
        return self.count() == 0
