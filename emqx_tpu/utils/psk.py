"""TLS-PSK identity store.

Parity: apps/emqx/src/emqx_psk.erl — a table of identity -> pre-shared key
bootstrapped from a colon-separated file, consulted by the TLS handshake's
psk lookup. Python's ssl module grows PSK callbacks in 3.13
(`SSLContext.set_psk_server_callback`); on earlier runtimes the store and
its file format are fully functional and `attach()` reports unsupported,
matching how the reference gates quicer/bcrypt behind build profiles.
"""

from __future__ import annotations

import binascii
import ssl
from typing import Optional


class PskStore:
    def __init__(self):
        self._keys: dict[str, bytes] = {}

    # file format (emqx_psk.erl init/bootstrap): one "identity:hexkey" per
    # line, '#' comments allowed
    def load_file(self, path: str, separator: str = ":") -> int:
        n = 0
        with open(path, encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line or line.startswith("#"):
                    continue
                ident, _, key = line.partition(separator)
                if not key:
                    continue
                self.insert(ident.strip(), key.strip())
                n += 1
        return n

    def insert(self, identity: str, hexkey: str) -> None:
        self._keys[identity] = binascii.unhexlify(hexkey)

    def delete(self, identity: str) -> bool:
        return self._keys.pop(identity, None) is not None

    def lookup(self, identity: str) -> Optional[bytes]:
        return self._keys.get(identity)

    def all(self) -> list[str]:
        return sorted(self._keys)

    # ---- ssl integration (requires python >= 3.13) ----------------------
    @staticmethod
    def supported() -> bool:
        return hasattr(ssl.SSLContext, "set_psk_server_callback")

    def attach(self, ctx: ssl.SSLContext) -> bool:
        """Install the identity lookup on a server context; False when the
        runtime's ssl module has no PSK support."""
        if not self.supported():
            return False
        ctx.set_psk_server_callback(
            lambda identity: self.lookup(identity or "") or b"")
        return True
