"""Shared JSON-safe wire encoding for binary-bearing structures.

One convention used by the cluster rpc plane, the bridge replay queue and
the persistence snapshots/WAL: bytes become {"$b": base64}, sets become
{"$set": [...]}. Changing the convention here changes it everywhere.
"""

from __future__ import annotations

import base64
from typing import Any


def enc(obj: Any) -> Any:
    """Deep-encode for json.dumps."""
    if isinstance(obj, (bytes, bytearray)):
        return {"$b": base64.b64encode(bytes(obj)).decode()}
    if isinstance(obj, dict):
        return {k: enc(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [enc(v) for v in obj]
    if isinstance(obj, set):
        return {"$set": [enc(v) for v in sorted(obj, key=repr)]}
    return obj


def dec(obj: Any) -> Any:
    """Deep-decode json.loads output."""
    if isinstance(obj, dict):
        if "$b" in obj and len(obj) == 1:
            return base64.b64decode(obj["$b"])
        if "$set" in obj and len(obj) == 1:
            return set(dec(v) for v in obj["$set"])
        return {k: dec(v) for k, v in obj.items()}
    if isinstance(obj, list):
        return [dec(v) for v in obj]
    return obj


def enc_default(o: Any) -> Any:
    """json.dumps(default=...) shim for shallow callers."""
    if isinstance(o, (bytes, bytearray)):
        return {"$b": base64.b64encode(bytes(o)).decode()}
    if isinstance(o, set):
        return sorted(o, key=repr)
    raise TypeError(repr(o))
