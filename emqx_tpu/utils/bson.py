"""Minimal BSON encode/decode for the MongoDB connector.

Parity note: the reference reaches MongoDB through the mongodb Erlang
driver (apps/emqx_connector/src/emqx_connector_mongo.erl); there is no
Python driver in this environment, so the wire format is implemented
directly. Covers the types MQTT authn/authz documents use: document,
array, utf8 string, int32/int64, double, bool, null, binary, ObjectId
(passed through as 12 raw bytes), UTC datetime (as int ms).
"""

from __future__ import annotations

import struct
from typing import Any

_E_DOUBLE = 0x01
_E_STRING = 0x02
_E_DOC = 0x03
_E_ARRAY = 0x04
_E_BINARY = 0x05
_E_OBJECTID = 0x07
_E_BOOL = 0x08
_E_DATETIME = 0x09
_E_NULL = 0x0A
_E_INT32 = 0x10
_E_INT64 = 0x12


class ObjectId:
    __slots__ = ("raw",)

    def __init__(self, raw: bytes):
        if len(raw) != 12:
            raise ValueError("ObjectId must be 12 bytes")
        self.raw = raw

    def __eq__(self, other):
        return isinstance(other, ObjectId) and other.raw == self.raw

    def __hash__(self):
        return hash(self.raw)

    def __repr__(self):
        return f"ObjectId({self.raw.hex()})"


def _encode_value(key: str, val: Any) -> bytes:
    kb = key.encode() + b"\x00"
    if isinstance(val, bool):
        return bytes([_E_BOOL]) + kb + (b"\x01" if val else b"\x00")
    if isinstance(val, int):
        if -(1 << 31) <= val < (1 << 31):
            return bytes([_E_INT32]) + kb + struct.pack("<i", val)
        return bytes([_E_INT64]) + kb + struct.pack("<q", val)
    if isinstance(val, float):
        return bytes([_E_DOUBLE]) + kb + struct.pack("<d", val)
    if isinstance(val, str):
        sb = val.encode()
        return bytes([_E_STRING]) + kb + \
            struct.pack("<i", len(sb) + 1) + sb + b"\x00"
    if val is None:
        return bytes([_E_NULL]) + kb
    if isinstance(val, (bytes, bytearray)):
        return bytes([_E_BINARY]) + kb + \
            struct.pack("<i", len(val)) + b"\x00" + bytes(val)
    if isinstance(val, ObjectId):
        return bytes([_E_OBJECTID]) + kb + val.raw
    if isinstance(val, dict):
        return bytes([_E_DOC]) + kb + encode(val)
    if isinstance(val, (list, tuple)):
        doc = {str(i): v for i, v in enumerate(val)}
        return bytes([_E_ARRAY]) + kb + encode(doc)
    raise TypeError(f"cannot BSON-encode {type(val).__name__}")


def encode(doc: dict) -> bytes:
    body = b"".join(_encode_value(str(k), v) for k, v in doc.items())
    return struct.pack("<i", len(body) + 5) + body + b"\x00"


def _decode_value(etype: int, data: bytes, pos: int) -> tuple[Any, int]:
    if etype == _E_DOUBLE:
        return struct.unpack_from("<d", data, pos)[0], pos + 8
    if etype == _E_STRING:
        n = struct.unpack_from("<i", data, pos)[0]
        s = data[pos + 4:pos + 4 + n - 1].decode()
        return s, pos + 4 + n
    if etype in (_E_DOC, _E_ARRAY):
        n = struct.unpack_from("<i", data, pos)[0]
        sub, _ = _decode_doc(data[pos:pos + n])
        if etype == _E_ARRAY:
            return [sub[str(i)] for i in range(len(sub))], pos + n
        return sub, pos + n
    if etype == _E_BINARY:
        n = struct.unpack_from("<i", data, pos)[0]
        return bytes(data[pos + 5:pos + 5 + n]), pos + 5 + n
    if etype == _E_OBJECTID:
        return ObjectId(bytes(data[pos:pos + 12])), pos + 12
    if etype == _E_BOOL:
        return data[pos] != 0, pos + 1
    if etype == _E_DATETIME:
        return struct.unpack_from("<q", data, pos)[0], pos + 8
    if etype == _E_NULL:
        return None, pos
    if etype == _E_INT32:
        return struct.unpack_from("<i", data, pos)[0], pos + 4
    if etype == _E_INT64:
        return struct.unpack_from("<q", data, pos)[0], pos + 8
    raise ValueError(f"unsupported BSON element type 0x{etype:02x}")


def _decode_doc(data: bytes) -> tuple[dict, int]:
    total = struct.unpack_from("<i", data, 0)[0]
    pos = 4
    out: dict = {}
    while pos < total - 1:
        etype = data[pos]
        pos += 1
        end = data.index(b"\x00", pos)
        key = data[pos:end].decode()
        pos = end + 1
        out[key], pos = _decode_value(etype, data, pos)
    return out, total


def decode(data: bytes) -> dict:
    doc, _ = _decode_doc(data)
    return doc
