"""SCRAM (RFC 5802) client + server state machines, SHA-1/SHA-256.

Parity: the reference's enhanced-auth SCRAM backend
(apps/emqx_authn/src/enhanced_authn/emqx_enhanced_authn_scram_mnesia.erl,
delegating to the esasl dep) — here a self-contained implementation used
by three consumers: the MQTT5 enhanced-auth authenticator, the PostgreSQL
connector (SCRAM-SHA-256 SASL auth), and the MongoDB connector
(saslStart/saslContinue).

Credential storage is the standard server-side tuple
(stored_key, server_key, salt, iteration_count) — the plaintext password
never persists, matching the scram_user_credentail record.
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import os
from typing import Optional

ALGOS = {"sha1": hashlib.sha1, "sha256": hashlib.sha256,
         "sha512": hashlib.sha512}


def _h(algo: str, data: bytes) -> bytes:
    return ALGOS[algo](data).digest()


def _hmac(algo: str, key: bytes, msg: bytes) -> bytes:
    return hmac.new(key, msg, ALGOS[algo]).digest()


def _xor(a: bytes, b: bytes) -> bytes:
    return bytes(x ^ y for x, y in zip(a, b))


def salted_password(algo: str, password: bytes, salt: bytes,
                    iterations: int) -> bytes:
    return hashlib.pbkdf2_hmac(algo, password, salt, iterations)


def derive_keys(algo: str, salted: bytes) -> tuple[bytes, bytes]:
    """-> (stored_key, server_key)"""
    client_key = _hmac(algo, salted, b"Client Key")
    server_key = _hmac(algo, salted, b"Server Key")
    return _h(algo, client_key), server_key


def make_credentials(password: str, algo: str = "sha256",
                     iterations: int = 4096,
                     salt: Optional[bytes] = None) -> dict:
    """Server-side stored credential for a new user."""
    salt = salt if salt is not None else os.urandom(16)
    salted = salted_password(algo, password.encode(), salt, iterations)
    stored_key, server_key = derive_keys(algo, salted)
    return {"stored_key": stored_key, "server_key": server_key,
            "salt": salt, "iteration_count": iterations, "algorithm": algo}


def _nonce() -> str:
    return base64.b64encode(os.urandom(18)).decode()


def _parse_attrs(msg: str) -> dict[str, str]:
    out: dict[str, str] = {}
    for part in msg.split(","):
        if len(part) >= 2 and part[1] == "=":
            out[part[0]] = part[2:]
    return out


def _saslname_decode(name: str) -> str:
    return name.replace("=2C", ",").replace("=3D", "=")


def _saslname_encode(name: str) -> str:
    return name.replace("=", "=3D").replace(",", "=2C")


class ScramError(Exception):
    pass


class ScramClient:
    """Client side: first() -> server-first -> final() -> verify server."""

    def __init__(self, username: str, password: str, algo: str = "sha256",
                 nonce: Optional[str] = None):
        self.algo = algo
        self.username = username
        self.password = password
        self.cnonce = nonce or _nonce()
        self._client_first_bare = ""
        self._auth_message = b""
        self._server_signature = b""

    def first(self) -> str:
        self._client_first_bare = \
            f"n={_saslname_encode(self.username)},r={self.cnonce}"
        return "n,," + self._client_first_bare

    def final(self, server_first: str) -> str:
        attrs = _parse_attrs(server_first)
        nonce = attrs.get("r", "")
        if not nonce.startswith(self.cnonce):
            raise ScramError("server nonce does not extend client nonce")
        salt = base64.b64decode(attrs["s"])
        iters = int(attrs["i"])
        channel = base64.b64encode(b"n,,").decode()
        final_bare = f"c={channel},r={nonce}"
        self._auth_message = ",".join(
            [self._client_first_bare, server_first, final_bare]).encode()
        salted = salted_password(self.algo, self.password.encode(),
                                 salt, iters)
        client_key = _hmac(self.algo, salted, b"Client Key")
        stored_key = _h(self.algo, client_key)
        signature = _hmac(self.algo, stored_key, self._auth_message)
        proof = _xor(client_key, signature)
        server_key = _hmac(self.algo, salted, b"Server Key")
        self._server_signature = _hmac(self.algo, server_key,
                                       self._auth_message)
        return final_bare + ",p=" + base64.b64encode(proof).decode()

    def verify_server(self, server_final: str) -> bool:
        attrs = _parse_attrs(server_final)
        if "e" in attrs:
            return False
        got = base64.b64decode(attrs.get("v", ""))
        return hmac.compare_digest(got, self._server_signature)


class ScramServer:
    """Server side: challenge(client-first) -> server-first;
    finish(client-final) -> server-final (or raise ScramError).

    `lookup` maps username -> credential dict from make_credentials
    (or None for unknown users).
    """

    def __init__(self, lookup, algo: str = "sha256",
                 nonce: Optional[str] = None):
        self.lookup = lookup
        self.algo = algo
        self.snonce = nonce or _nonce()
        self.username = ""
        self._cred: Optional[dict] = None
        self._client_first_bare = ""
        self._server_first = ""
        self._nonce = ""

    def challenge(self, client_first: str) -> str:
        if client_first.startswith(("n,,", "y,,")):
            bare = client_first[3:]
        elif client_first.startswith(("n,", "y,")):
            # gs2 header with authzid: strip up to the 2nd comma
            bare = client_first.split(",", 2)[2]
        else:
            raise ScramError("channel binding not supported")
        attrs = _parse_attrs(bare)
        if "n" not in attrs or "r" not in attrs:
            raise ScramError("malformed client-first message")
        self.username = _saslname_decode(attrs["n"])
        self._client_first_bare = bare
        self._cred = self.lookup(self.username)
        if self._cred is None:
            raise ScramError("unknown user")
        if self._cred.get("algorithm", self.algo) != self.algo:
            raise ScramError("algorithm mismatch")
        self._nonce = attrs["r"] + self.snonce
        salt_b64 = base64.b64encode(self._cred["salt"]).decode()
        self._server_first = (f"r={self._nonce},s={salt_b64},"
                              f"i={self._cred['iteration_count']}")
        return self._server_first

    def finish(self, client_final: str) -> str:
        attrs = _parse_attrs(client_final)
        if attrs.get("r") != self._nonce:
            raise ScramError("nonce mismatch")
        proof = base64.b64decode(attrs.get("p", ""))
        final_bare = client_final[:client_final.rindex(",p=")]
        auth_message = ",".join(
            [self._client_first_bare, self._server_first,
             final_bare]).encode()
        stored_key = self._cred["stored_key"]
        signature = _hmac(self.algo, stored_key, auth_message)
        client_key = _xor(proof, signature)
        if not hmac.compare_digest(_h(self.algo, client_key), stored_key):
            raise ScramError("invalid proof")
        server_sig = _hmac(self.algo, self._cred["server_key"], auth_message)
        return "v=" + base64.b64encode(server_sig).decode()
