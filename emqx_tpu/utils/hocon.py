"""HOCON-lite: the configuration file format loader.

Parity: the reference boots from HOCON files through the hocon dep
(emqx_config:init_load, apps/emqx/src/emqx_config.erl:20-27;
emqx_machine_app load_config_files). This implements the HOCON subset
those files use:

- objects `{}` (root braces optional), arrays `[]`
- `k = v`, `k: v`, `k { ... }`, dotted path keys `a.b.c = v`
- `k += v` array append
- duplicate object keys deep-merge; later scalars win
- comments `#` / `//`, trailing commas, newline-separated values
- quoted / triple-quoted / unquoted strings, numbers, bool, null
- durations ("10s", "2m", "1h", "1d", "100ms") and byte sizes
  ("16KB", "1MB") via coercion helpers used by the schema check
- `include "relative/path.conf"`
- substitutions `${a.b.c}` (from the document root) and optional
  `${?NAME}` (document root, then environment, else dropped)

`loads`/`load` produce plain dicts; `dumps` renders a dict back (used to
persist runtime overrides, the emqx_override.conf analog).
"""

from __future__ import annotations

import os
import re
from typing import Any, Optional


class HoconError(ValueError):
    pass


_DURATION_RE = re.compile(
    r"^(\d+(?:\.\d+)?)(ms|s|m|h|d|w)$")
_SIZE_RE = re.compile(r"^(\d+(?:\.\d+)?)(kb|mb|gb|b)$", re.IGNORECASE)
_DURATION_UNITS = {"ms": 0.001, "s": 1, "m": 60, "h": 3600, "d": 86400,
                   "w": 604800}
_SIZE_UNITS = {"b": 1, "kb": 1024, "mb": 1024 ** 2, "gb": 1024 ** 3}


def parse_duration(s: str) -> Optional[float]:
    """"30s" -> 30.0; "100ms" -> 0.1; None when not a duration string."""
    m = _DURATION_RE.match(s.strip())
    if not m:
        return None
    val = float(m.group(1)) * _DURATION_UNITS[m.group(2)]
    return val


def parse_size(s: str) -> Optional[int]:
    """"16KB" -> 16384; None when not a size string."""
    m = _SIZE_RE.match(s.strip())
    if not m:
        return None
    return int(float(m.group(1)) * _SIZE_UNITS[m.group(2).lower()])


class _Sub:
    """Unresolved ${path} marker."""

    __slots__ = ("path", "optional")

    def __init__(self, path: str, optional: bool):
        self.path = path
        self.optional = optional


class _Parser:
    def __init__(self, text: str, basedir: str = "."):
        self.s = text
        self.n = len(text)
        self.i = 0
        self.basedir = basedir

    # ---- low-level ----
    def _err(self, msg: str) -> HoconError:
        line = self.s.count("\n", 0, self.i) + 1
        return HoconError(f"line {line}: {msg}")

    def _peek(self) -> str:
        return self.s[self.i] if self.i < self.n else ""

    def _skip_ws(self, newlines: bool = True) -> None:
        while self.i < self.n:
            c = self.s[self.i]
            if c == "#" or self.s[self.i:self.i + 2] == "//":
                while self.i < self.n and self.s[self.i] != "\n":
                    self.i += 1
            elif c in " \t\r" or (newlines and c == "\n"):
                self.i += 1
            else:
                break

    # ---- tokens ----
    def _quoted(self) -> str:
        if self.s.startswith('"""', self.i):
            end = self.s.find('"""', self.i + 3)
            if end < 0:
                raise self._err("unterminated triple-quoted string")
            out = self.s[self.i + 3:end]
            self.i = end + 3
            return out
        assert self.s[self.i] == '"'
        self.i += 1
        out = []
        while self.i < self.n:
            c = self.s[self.i]
            if c == '"':
                self.i += 1
                return "".join(out)
            if c == "\\":
                self.i += 1
                esc = self.s[self.i]
                out.append({"n": "\n", "t": "\t", "r": "\r", '"': '"',
                            "\\": "\\", "/": "/"}.get(esc, esc))
                self.i += 1
            else:
                out.append(c)
                self.i += 1
        raise self._err("unterminated string")

    def _key(self) -> str:
        self._skip_ws()
        if self._peek() == '"':
            return self._quoted()
        start = self.i
        while self.i < self.n and self.s[self.i] not in " \t\n=:{+":
            self.i += 1
        key = self.s[start:self.i].strip()
        if not key:
            raise self._err("expected a key")
        return key

    def _unquoted_value(self, stop_extra: str) -> Any:
        start = self.i
        while self.i < self.n:
            c = self.s[self.i]
            if c in "\n#" + stop_extra or self.s[self.i:self.i + 2] == "//":
                break
            self.i += 1
        raw = self.s[start:self.i].strip()
        return _coerce_scalar(raw, self._err)

    # ---- values ----
    def _value(self, stop_extra: str = "") -> Any:
        self._skip_ws(newlines=False)
        c = self._peek()
        if c == "{":
            return self._object()
        if c == "[":
            return self._array()
        if c == '"':
            s = self._quoted()
            # adjacent-string concatenation is rare in emqx confs; a quoted
            # string is the whole value
            return s
        if self.s.startswith("${", self.i):
            end = self.s.index("}", self.i)
            inner = self.s[self.i + 2:end]
            self.i = end + 1
            optional = inner.startswith("?")
            return _Sub(inner[1:] if optional else inner, optional)
        return self._unquoted_value(stop_extra)

    def _array(self) -> list:
        assert self._peek() == "["
        self.i += 1
        out: list = []
        while True:
            self._skip_ws()
            if self._peek() == "":
                raise self._err("unterminated array")
            if self._peek() == "]":
                self.i += 1
                return out
            out.append(self._value(stop_extra=",]"))
            self._skip_ws(newlines=False)
            if self._peek() == ",":
                self.i += 1

    def _object(self, root: bool = False) -> dict:
        if not root:
            assert self._peek() == "{"
            self.i += 1
        out: dict = {}
        while True:
            self._skip_ws()
            c = self._peek()
            if c == "":
                if root:
                    return out
                raise self._err("unterminated object")
            if c == "}":
                if root:
                    raise self._err("unexpected '}'")
                self.i += 1
                return out
            if c == ",":
                self.i += 1
                continue
            # include statement
            if self.s.startswith("include", self.i) and \
                    self.s[self.i + 7:self.i + 8] in (" ", "\t", '"'):
                self.i += 7
                self._skip_ws(newlines=False)
                if self._peek() != '"':
                    raise self._err("include expects a quoted path")
                rel = self._quoted()
                path = os.path.join(self.basedir, rel)
                with open(path, "r", encoding="utf-8") as f:
                    sub = _Parser(f.read(),
                                  os.path.dirname(path) or ".")._object(
                                      root=True)
                _merge_into(out, sub)
                continue
            key = self._key()
            self._skip_ws(newlines=False)
            append = False
            if self.s.startswith("+=", self.i):
                append = True
                self.i += 2
            elif self._peek() in "=:":
                self.i += 1
            elif self._peek() != "{":
                raise self._err(f"expected '=', ':' or '{{' after {key!r}")
            val = self._value(stop_extra=",}")
            _assign(out, key.split("."), val, append, self._err)


def _coerce_scalar(raw: str, err) -> Any:
    if raw == "":
        raise err("empty value")
    low = raw.lower()
    if low in ("true", "on", "yes"):
        return True
    if low in ("false", "off", "no"):
        return False
    if low == "null":
        return None
    try:
        return int(raw)
    except ValueError:
        pass
    try:
        return float(raw)
    except ValueError:
        pass
    return raw


def _assign(obj: dict, path: list[str], val: Any, append: bool,
            err) -> None:
    cur = obj
    for p in path[:-1]:
        nxt = cur.get(p)
        if not isinstance(nxt, dict):
            nxt = cur[p] = {}
        cur = nxt
    leaf = path[-1]
    if append:
        existing = cur.get(leaf)
        if existing is None:
            cur[leaf] = [val]
        elif isinstance(existing, list):
            existing.append(val)
        else:
            raise err(f"cannot += into non-array key {leaf!r}")
    elif isinstance(val, dict) and isinstance(cur.get(leaf), dict):
        _merge_into(cur[leaf], val)
    else:
        cur[leaf] = val


def _merge_into(base: dict, over: dict) -> None:
    for k, v in over.items():
        if isinstance(v, dict) and isinstance(base.get(k), dict):
            _merge_into(base[k], v)
        else:
            base[k] = v


def _lookup(root: dict, path: str) -> Any:
    cur: Any = root
    for p in path.split("."):
        if not isinstance(cur, dict) or p not in cur:
            raise KeyError(path)
        cur = cur[p]
    return cur


def _resolve(node: Any, root: dict) -> Any:
    if isinstance(node, _Sub):
        try:
            val = _lookup(root, node.path)
            return _resolve(val, root) if isinstance(val, (_Sub, dict, list)) \
                else val
        except KeyError:
            env = os.environ.get(node.path)
            if env is not None:
                return _coerce_scalar(env, HoconError)
            if node.optional:
                return None
            raise HoconError(f"unresolved substitution ${{{node.path}}}")
    if isinstance(node, dict):
        out = {}
        for k, v in node.items():
            rv = _resolve(v, root)
            if not (isinstance(v, _Sub) and v.optional and rv is None):
                out[k] = rv
        return out
    if isinstance(node, list):
        return [_resolve(v, root) for v in node
                if not (isinstance(v, _Sub) and v.optional
                        and _try_resolve(v, root) is None)]
    return node


def _try_resolve(sub: _Sub, root: dict):
    try:
        return _resolve(sub, root)
    except HoconError:
        return None


def loads(text: str, basedir: str = ".") -> dict:
    raw = _Parser(text, basedir)._object(root=True)
    return _resolve(raw, raw)


def load(path: str) -> dict:
    with open(path, "r", encoding="utf-8") as f:
        return loads(f.read(), os.path.dirname(path) or ".")


# ---------------------------------------------------------------------------
# rendering (override persistence)
# ---------------------------------------------------------------------------

_BARE_RE = re.compile(r"^[A-Za-z0-9_\-]+$")


def _render(val: Any, indent: int) -> str:
    pad = "  " * indent
    if isinstance(val, dict):
        if not val:
            return "{}"
        inner = "".join(
            f"{pad}  {_render_key(k)} = {_render(v, indent + 1)}\n"
            for k, v in val.items())
        return "{\n" + inner + pad + "}"
    if isinstance(val, list):
        return "[" + ", ".join(_render(v, indent) for v in val) + "]"
    if isinstance(val, bool):
        return "true" if val else "false"
    if val is None:
        return "null"
    if isinstance(val, (int, float)):
        return str(val)
    s = str(val)
    # only render bare if the parser would read the SAME string back:
    # "0", "true", "off" etc. coerce to typed values on load, which would
    # silently change a string's type across an override persist/reload
    # cycle (found by the dumps→loads property test)
    if _BARE_RE.match(s) and isinstance(_coerce_scalar(s, HoconError), str):
        return s
    return '"' + s.replace("\\", "\\\\").replace('"', '\\"') + '"'


def _render_key(k: str) -> str:
    return k if _BARE_RE.match(k) else '"' + k + '"'


def dumps(conf: dict) -> str:
    return "".join(f"{_render_key(k)} = {_render(v, 0)}\n"
                   for k, v in conf.items())
