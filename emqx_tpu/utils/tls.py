"""TLS helpers: listener/client SSLContext construction + peer-cert info.

Parity: apps/emqx/src/emqx_tls_lib.erl (version/cipher selection) and the
listener ssl option blocks of emqx_schema.erl / emqx_listeners.erl:126-138
(certfile/keyfile/cacertfile/verify/fail_if_no_peer_cert). The reference
rides Erlang's ssl app; here the asyncio TLS transport consumes a stdlib
`ssl.SSLContext` built from the same option names.
"""

from __future__ import annotations

import ssl
from typing import Optional

_VERSION_MAP = {
    "tlsv1.2": ssl.TLSVersion.TLSv1_2,
    "tlsv1.3": ssl.TLSVersion.TLSv1_3,
}


def _apply_versions(ctx: ssl.SSLContext, versions) -> None:
    if not versions:
        ctx.minimum_version = ssl.TLSVersion.TLSv1_2
        return
    vs = [_VERSION_MAP[v.lower()] for v in versions if v.lower()
          in _VERSION_MAP]
    if vs:
        ctx.minimum_version = min(vs)
        ctx.maximum_version = max(vs)


def make_server_context(opts: dict) -> ssl.SSLContext:
    """Listener ssl options -> server SSLContext.

    opts keys (emqx_schema ssl block names): certfile, keyfile, password,
    cacertfile, verify ('verify_none' | 'verify_peer'),
    fail_if_no_peer_cert, versions (['tlsv1.2','tlsv1.3']), ciphers.
    """
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
    ctx.load_cert_chain(opts["certfile"], opts.get("keyfile"),
                        password=opts.get("password"))
    _apply_versions(ctx, opts.get("versions"))
    if opts.get("ciphers"):
        ctx.set_ciphers(":".join(opts["ciphers"])
                        if isinstance(opts["ciphers"], list)
                        else opts["ciphers"])
    if opts.get("cacertfile"):
        ctx.load_verify_locations(opts["cacertfile"])
    if opts.get("verify") == "verify_peer":
        # fail_if_no_peer_cert=false maps to OPTIONAL client certs
        ctx.verify_mode = (ssl.CERT_REQUIRED
                           if opts.get("fail_if_no_peer_cert")
                           else ssl.CERT_OPTIONAL)
    else:
        ctx.verify_mode = ssl.CERT_NONE
    return ctx


def make_client_context(opts: Optional[dict] = None) -> ssl.SSLContext:
    """Client-side context (MQTT bridge egress, test clients)."""
    opts = opts or {}
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
    _apply_versions(ctx, opts.get("versions"))
    if opts.get("cacertfile"):
        ctx.load_verify_locations(opts["cacertfile"])
        ctx.verify_mode = ssl.CERT_REQUIRED
        ctx.check_hostname = bool(opts.get("server_name_indication", False))
    else:
        ctx.check_hostname = False
        ctx.verify_mode = ssl.CERT_NONE
    if opts.get("certfile"):
        ctx.load_cert_chain(opts["certfile"], opts.get("keyfile"),
                            password=opts.get("password"))
    return ctx


def peer_cert_info(transport_or_writer) -> Optional[dict]:
    """Extract the client certificate (dict form) from a TLS transport.

    Returns None on plain TCP or when no client cert was presented.
    Used by the channel for peer_cert_as_username/clientid
    (emqx_channel peer-cert enrichment; emqx_schema.erl zone opts).
    """
    get = getattr(transport_or_writer, "get_extra_info", None)
    if get is None:
        return None
    cert = get("peercert")
    if not cert:
        return None
    out = {"raw": cert}
    for rdn in cert.get("subject", ()):  # ((('commonName','x'),), ...)
        for k, v in rdn:
            out.setdefault(k, v)
    return out


def cert_field(info: Optional[dict], source: str) -> Optional[str]:
    """Map a peer_cert_as_* source to a value: 'cn' | 'dn'."""
    if not info:
        return None
    if source == "cn":
        return info.get("commonName")
    if source == "dn":
        subj = info.get("raw", {}).get("subject", ())
        return ",".join(f"{k}={v}" for rdn in subj for k, v in rdn)
    return None


# ---- self-signed material (dev listeners + test suites) -----------------

def generate_self_signed(dirpath: str, cn: str = "emqx-tpu",
                         *, ca_cn: str = "emqx-tpu-ca",
                         client_cn: Optional[str] = None) -> dict:
    """Write a CA + server cert (+ optional client cert) under `dirpath`.

    Returns {'cacertfile', 'certfile', 'keyfile'[, 'client_certfile',
    'client_keyfile']}. Test-suite parity: the reference ships static
    certs in apps/emqx/etc/certs; here they are generated on demand.
    """
    import datetime
    import os

    from cryptography import x509
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import rsa
    from cryptography.x509.oid import NameOID

    os.makedirs(dirpath, exist_ok=True)
    now = datetime.datetime.now(datetime.timezone.utc)

    def _key():
        return rsa.generate_private_key(public_exponent=65537, key_size=2048)

    def _name(common):
        return x509.Name(
            [x509.NameAttribute(NameOID.COMMON_NAME, common)])

    def _write(path, data):
        with open(os.path.join(dirpath, path), "wb") as f:
            f.write(data)
        return os.path.join(dirpath, path)

    def _pem_key(k):
        return k.private_bytes(
            serialization.Encoding.PEM,
            serialization.PrivateFormat.TraditionalOpenSSL,
            serialization.NoEncryption())

    ca_key = _key()
    ca_cert = (x509.CertificateBuilder()
               .subject_name(_name(ca_cn)).issuer_name(_name(ca_cn))
               .public_key(ca_key.public_key())
               .serial_number(x509.random_serial_number())
               .not_valid_before(now - datetime.timedelta(days=1))
               .not_valid_after(now + datetime.timedelta(days=365))
               .add_extension(x509.BasicConstraints(ca=True,
                                                    path_length=None),
                              critical=True)
               .sign(ca_key, hashes.SHA256()))
    out = {"cacertfile": _write("ca.pem", ca_cert.public_bytes(
        serialization.Encoding.PEM))}

    def _issue(common, keyfile, certfile, san_localhost=False):
        k = _key()
        builder = (x509.CertificateBuilder()
                   .subject_name(_name(common)).issuer_name(_name(ca_cn))
                   .public_key(k.public_key())
                   .serial_number(x509.random_serial_number())
                   .not_valid_before(now - datetime.timedelta(days=1))
                   .not_valid_after(now + datetime.timedelta(days=365)))
        if san_localhost:
            import ipaddress
            builder = builder.add_extension(
                x509.SubjectAlternativeName(
                    [x509.DNSName("localhost"),
                     x509.IPAddress(ipaddress.ip_address("127.0.0.1"))]),
                critical=False)
        cert = builder.sign(ca_key, hashes.SHA256())
        return (_write(keyfile, _pem_key(k)),
                _write(certfile, cert.public_bytes(
                    serialization.Encoding.PEM)))

    out["keyfile"], out["certfile"] = _issue(cn, "server.key", "server.pem",
                                             san_localhost=True)
    if client_cn:
        out["client_keyfile"], out["client_certfile"] = _issue(
            client_cn, "client.key", "client.pem")
    return out
