"""Minimal asyncio HTTP/1.1 client (no external deps).

Role parity: the reference's `ehttpc` pool used by emqx_authn/authz HTTP
sources and the HTTP connector (apps/emqx_connector/src/emqx_connector_http.erl).
Supports GET/POST with JSON or form bodies over plain TCP; enough surface
for localhost auth/webhook backends and for the in-repo test servers.
"""

from __future__ import annotations

import asyncio
import json as _json
from typing import Optional
from urllib.parse import urlencode, urlsplit


class HttpResponse:
    def __init__(self, status: int, headers: dict[str, str], body: bytes):
        self.status = status
        self.headers = headers
        self.body = body

    def json(self):
        return _json.loads(self.body.decode())


async def request(method: str, url: str, *,
                  headers: Optional[dict] = None,
                  body: Optional[bytes] = None,
                  json: Optional[dict] = None,
                  form: Optional[dict] = None,
                  timeout: float = 5.0) -> HttpResponse:
    parts = urlsplit(url)
    if parts.scheme not in ("http", ""):
        raise ValueError(f"unsupported scheme {parts.scheme!r}")
    host = parts.hostname or "127.0.0.1"
    port = parts.port or 80
    path = parts.path or "/"
    if parts.query:
        path += "?" + parts.query
    hdrs = {"host": f"{host}:{port}", "connection": "close"}
    if json is not None:
        body = _json.dumps(json).encode()
        hdrs["content-type"] = "application/json"
    elif form is not None:
        body = urlencode(form).encode()
        hdrs["content-type"] = "application/x-www-form-urlencoded"
    if body:
        hdrs["content-length"] = str(len(body))
    hdrs.update({k.lower(): v for k, v in (headers or {}).items()})

    reader, writer = await asyncio.wait_for(
        asyncio.open_connection(host, port), timeout)
    try:
        head = f"{method.upper()} {path} HTTP/1.1\r\n" + "".join(
            f"{k}: {v}\r\n" for k, v in hdrs.items()) + "\r\n"
        writer.write(head.encode() + (body or b""))
        await writer.drain()
        raw = await asyncio.wait_for(reader.read(), timeout)
    finally:
        writer.close()
        try:
            await writer.wait_closed()
        except Exception:  # noqa: BLE001 — best-effort close on a one-
            pass           # shot client socket; the response is in hand
    header_blob, _, rest = raw.partition(b"\r\n\r\n")
    lines = header_blob.decode("latin-1").split("\r\n")
    status = int(lines[0].split()[1])
    rhdrs = {}
    for ln in lines[1:]:
        k, _, v = ln.partition(":")
        rhdrs[k.strip().lower()] = v.strip()
    if rhdrs.get("transfer-encoding", "").lower() == "chunked":
        rest = _dechunk(rest)
    return HttpResponse(status, rhdrs, rest)


async def templated_request(method: str, url: str, body_template: dict,
                            subs: dict, *, headers: Optional[dict] = None,
                            timeout: float = 5.0,
                            transport=None) -> HttpResponse:
    """Fill %-placeholders in a body template and issue the request —
    GET encodes the body as a query string, everything else POSTs JSON.
    Shared by the HTTP authenticator and the HTTP ACL source (the
    reference's emqx_authn_http / emqx_authz_http both do exactly this
    placeholder-fill + request step)."""
    transport = transport or request
    payload = {k: subs.get(v, v) if isinstance(v, str) else v
               for k, v in body_template.items()}
    if method.lower() == "get":
        from urllib.parse import urlencode
        return await transport("GET", url + "?" + urlencode(payload),
                               headers=headers, timeout=timeout)
    return await transport("POST", url, json=payload, headers=headers,
                           timeout=timeout)


def _dechunk(data: bytes) -> bytes:
    out = bytearray()
    while data:
        size_s, _, data = data.partition(b"\r\n")
        try:
            size = int(size_s.strip(), 16)
        except ValueError:
            break
        if size == 0:
            break
        out += data[:size]
        data = data[size + 2:]
    return bytes(out)
