"""Small asyncio compatibility helpers.

`timeout_after` is the Python 3.10-compatible stand-in for
``asyncio.timeout`` (3.11+): an async context manager that cancels the
enclosing task when the deadline passes and converts that cancellation
into ``asyncio.TimeoutError`` at the block's exit. cluster/rpc.py used
``asyncio.timeout`` directly, which made every cluster test fail at
import time on 3.10 boxes (AttributeError) — the "environmental"
failure set carried since the seed.

Semantics (the subset the repo needs, mirroring the stdlib manager):

- the block raises ``asyncio.TimeoutError`` when the deadline expires
  while the body is suspended at an await;
- a cancellation arriving from OUTSIDE the scope is NOT swallowed —
  only the scope's own deadline-cancel is converted (same idea as the
  stdlib's uncancel() accounting, implemented via the timed-out flag:
  when our handle never fired, the CancelledError propagates);
- the body finishing before the deadline cancels the timer and exits
  cleanly.

One nuance vs 3.11: if an external cancel races the deadline-cancel in
the same event-loop tick, the timeout wins (the stdlib would re-raise
CancelledError). The repo's two call sites (rpc connect/cast) treat
both outcomes as "give up on this channel", so the race is benign.
"""

from __future__ import annotations

import asyncio
from typing import Optional


class timeout_after:
    """``async with timeout_after(seconds): ...`` — 3.10-compatible
    ``asyncio.timeout``. ``seconds=None`` disables the deadline (the
    block runs unbounded, stdlib-compatible)."""

    def __init__(self, seconds: Optional[float]):
        self.seconds = seconds
        self._task: Optional[asyncio.Task] = None
        self._handle: Optional[asyncio.TimerHandle] = None
        self._timed_out = False

    def expired(self) -> bool:
        return self._timed_out

    async def __aenter__(self) -> "timeout_after":
        self._task = asyncio.current_task()
        if self.seconds is not None:
            loop = asyncio.get_running_loop()
            self._handle = loop.call_later(self.seconds,
                                           self._on_timeout)
        return self

    def _on_timeout(self) -> None:
        self._timed_out = True
        if self._task is not None:
            self._task.cancel()

    async def __aexit__(self, exc_type, exc, tb) -> bool:
        if self._handle is not None:
            self._handle.cancel()
            self._handle = None
        if self._timed_out and exc_type is asyncio.CancelledError:
            raise asyncio.TimeoutError from exc
        return False
