"""Password hashing/verification for the built-in authentication DB.

Parity: apps/emqx/src/emqx_passwd.erl — algorithms plain, md5, sha, sha256,
sha512, pbkdf2, with salt prefix/suffix placement. bcrypt (a C NIF in the
reference's cloud profile, rebar.config.erl:15-16) is gated: used when a
bcrypt module is importable, otherwise rejected at config time.
"""

from __future__ import annotations

import hashlib
import hmac
import os
from typing import Optional

ALGORITHMS = ("plain", "md5", "sha", "sha256", "sha512", "pbkdf2", "bcrypt")

try:                                    # optional C-backed bcrypt
    import bcrypt as _bcrypt            # pragma: no cover
except ImportError:
    _bcrypt = None


def gen_salt(n: int = 16) -> str:
    return os.urandom(n).hex()


def hash_password(algo: str, password: bytes, salt: str = "",
                  salt_position: str = "prefix",
                  iterations: int = 4096, dk_length: int = 32) -> str:
    """Returns the hex digest (or bcrypt hash string)."""
    if algo == "plain":
        return password.decode("utf-8", "surrogateescape")
    if algo == "bcrypt":
        if _bcrypt is None:
            raise ValueError("bcrypt not available in this build")
        return _bcrypt.hashpw(password, salt.encode() if salt
                              else _bcrypt.gensalt()).decode()
    if algo == "pbkdf2":
        dk = hashlib.pbkdf2_hmac("sha256", password, salt.encode(),
                                 iterations, dklen=dk_length)
        return dk.hex()
    salted = (salt.encode() + password if salt_position == "prefix"
              else password + salt.encode())
    if algo == "md5":
        return hashlib.md5(salted).hexdigest()
    if algo == "sha":
        return hashlib.sha1(salted).hexdigest()
    if algo == "sha256":
        return hashlib.sha256(salted).hexdigest()
    if algo == "sha512":
        return hashlib.sha512(salted).hexdigest()
    raise ValueError(f"unknown password hash algorithm {algo!r}")


def check_password(algo: str, stored: str, password: Optional[bytes],
                   salt: str = "", salt_position: str = "prefix",
                   iterations: int = 4096, dk_length: int = 32) -> bool:
    if password is None:
        return False
    if algo == "bcrypt":
        if _bcrypt is None:
            return False
        try:
            return _bcrypt.checkpw(password, stored.encode())
        except ValueError:
            return False
    got = hash_password(algo, password, salt, salt_position, iterations,
                        dk_length)
    return hmac.compare_digest(got, stored)
