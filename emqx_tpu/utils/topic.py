"""Topic algebra: tokenize, validate, wildcard-match, $share/$queue parsing.

Pure functions, no JAX. This module is the *conformance oracle* for the
device-side batched matcher (`emqx_tpu.ops.match`): randomized property tests
assert trie-match == brute-force `match()` over the same filter set.

Behavioral parity with the reference broker's topic algebra
(`/root/reference/apps/emqx/src/emqx_topic.erl`):

- levels split on ``/``; empty levels are real levels (``"/a"`` has 2 levels).
- ``+`` matches exactly one level (including an empty one); ``#`` matches the
  remaining levels *including zero* (``sport/#`` matches ``sport``).
- A topic NAME whose first byte is ``$`` never matches a filter whose first
  byte is ``+`` or ``#`` (root-level wildcard exclusion only; deeper levels
  starting with ``$`` are ordinary) — emqx_topic.erl:66-69.
- Filters: ``#`` only as the last level, ``+`` only alone in a level, no
  wildcard/NUL bytes inside a word; names additionally reject all wildcards —
  emqx_topic.erl:89-127.
- ``$share/<group>/<filter>`` and ``$queue/<filter>`` shared-subscription
  prefixes — emqx_topic.erl:197-220.
- Max topic length 65535 bytes — emqx_topic.erl:45.
"""

from __future__ import annotations

from typing import Iterable, Optional

MAX_TOPIC_LEN = 65535

PLUS = "+"
HASH = "#"
SHARE_PREFIX = "$share/"
QUEUE_PREFIX = "$queue/"


class TopicError(ValueError):
    """Invalid topic name or filter. `.code` mirrors the reference's error atoms."""

    def __init__(self, code: str, topic: str = ""):
        super().__init__(f"{code}: {topic!r}" if topic else code)
        self.code = code
        self.topic = topic


def tokens(topic: str) -> list[str]:
    """Split a topic into levels on '/'. '' yields ['']."""
    return topic.split("/")


# `words` is an alias: unlike the Erlang reference we keep '+'/'#'/'' as plain
# strings rather than atoms; all consumers compare strings.
words = tokens


def levels(topic: str) -> int:
    return len(tokens(topic))


def wildcard(topic: "str | Iterable[str]") -> bool:
    """Does the topic filter contain '+' or '#' as a whole level?"""
    ws = tokens(topic) if isinstance(topic, str) else topic
    return any(w == PLUS or w == HASH for w in ws)


def match(name: "str | list[str]", filt: "str | list[str]") -> bool:
    """Match a topic *name* against a topic *filter*.

    Accepts strings or pre-split word lists. The `$`-exclusion rule applies
    only when both are strings (root-level check on the raw first byte),
    mirroring the reference's binary-head clauses.
    """
    if isinstance(name, str) and isinstance(filt, str):
        if name[:1] == "$" and filt[:1] in (PLUS, HASH):
            return False
        return match_words(tokens(name), tokens(filt))
    n = tokens(name) if isinstance(name, str) else name
    f = tokens(filt) if isinstance(filt, str) else filt
    return match_words(n, f)


def match_words(n: list[str], f: list[str]) -> bool:
    """Word-level match; no `$` special-casing (caller's concern)."""
    i, j, ln, lf = 0, 0, len(n), len(f)
    while True:
        if j >= lf:
            return i >= ln
        fw = f[j]
        if fw == HASH:
            # '#' must be last in a valid filter; matches any tail incl. empty
            return True
        if i >= ln:
            return False
        if fw == PLUS or n[i] == fw:
            i += 1
            j += 1
        else:
            return False


def validate(topic: str, kind: str = "filter") -> bool:
    """Validate a topic filter or name; raises TopicError, returns True.

    kind: 'filter' (wildcards allowed) or 'name' (no wildcards).
    """
    if kind == "name" and topic and "#" not in topic \
            and "+" not in topic and "\x00" not in topic:
        # fast path for the publish hot loop: a clean NAME (no wildcard
        # or NUL bytes anywhere, empty levels allowed) needs no
        # tokenize/word-walk — only the length bound. Anything that
        # would be rejected falls through to the slow path so error
        # reasons stay exact.
        if len(topic.encode("utf-8")) > MAX_TOPIC_LEN:
            raise TopicError("topic_too_long", topic)
        return True
    if kind not in ("filter", "name"):
        raise ValueError(f"kind must be 'filter' or 'name', got {kind!r}")
    if topic == "":
        raise TopicError("empty_topic")
    if len(topic.encode("utf-8")) > MAX_TOPIC_LEN:
        raise TopicError("topic_too_long", topic)
    ws = tokens(topic)
    _validate_words(ws, topic)
    if kind == "name" and wildcard(ws):
        raise TopicError("topic_name_error", topic)
    return True


def _validate_words(ws: list[str], topic: str) -> None:
    last = len(ws) - 1
    for i, w in enumerate(ws):
        if w == HASH:
            if i != last:
                raise TopicError("topic_invalid_#", topic)
        elif w == PLUS or w == "":
            continue
        else:
            if any(c in ("#", "+", "\x00") for c in w):
                raise TopicError("topic_invalid_char", topic)


def parse(topic_filter: str, options: Optional[dict] = None) -> tuple[str, dict]:
    """Strip `$share/<group>/` / `$queue/` prefixes → (real_filter, options).

    options gains {'share': <group>} for shared subscriptions ('$queue' group
    for the $queue form). Nested share prefixes are invalid.
    """
    options = dict(options or {})
    if topic_filter.startswith(QUEUE_PREFIX):
        if "share" in options:
            raise TopicError("invalid_topic_filter", topic_filter)
        return parse(topic_filter[len(QUEUE_PREFIX):], {**options, "share": "$queue"})
    if topic_filter.startswith(SHARE_PREFIX):
        if "share" in options:
            raise TopicError("invalid_topic_filter", topic_filter)
        rest = topic_filter[len(SHARE_PREFIX):]
        group, sep, filt = rest.partition("/")
        if not sep:
            raise TopicError("invalid_topic_filter", topic_filter)
        if "+" in group or "#" in group:
            raise TopicError("invalid_topic_filter", topic_filter)
        return parse(filt, {**options, "share": group})
    return topic_filter, options


def join(ws: Iterable[str]) -> str:
    return "/".join(ws)


def prepend(prefix: Optional[str], topic: str) -> str:
    """Prepend a mountpoint prefix, ensuring exactly one '/' between parts."""
    if not prefix:
        return topic
    if prefix.endswith("/"):
        return prefix + topic
    return prefix + "/" + topic


def feed_var(var: str, val: str, topic: str) -> str:
    """Replace each whole level equal to `var` (e.g. '%c') with `val`."""
    return join(val if w == var else w for w in tokens(topic))


def systop(name: str, node: str = "emqx_tpu@127.0.0.1") -> str:
    return f"$SYS/brokers/{node}/{name}"
