"""Logging: JSON/text formatters with per-client metadata scoping.

Parity: emqx_logger.erl + emqx_logger_jsonfmt.erl /
emqx_logger_textfmt.erl — the reference scopes every log line inside a
connection process with clientid/peername metadata and offers a JSON
formatter for machine ingestion. asyncio has no process dictionary, so
the metadata rides a contextvar that each connection task sets once
(set_metadata_clientid / set_metadata_peername); a logging.Filter copies
it onto every record emitted from that task's context.
"""

from __future__ import annotations

import contextvars
import json
import logging
import time
from typing import Any, Optional

_log_metadata: contextvars.ContextVar[dict] = contextvars.ContextVar(
    "emqx_log_metadata", default={})


def set_metadata(**kv: Any) -> None:
    md = dict(_log_metadata.get())
    md.update(kv)
    _log_metadata.set(md)


def set_metadata_clientid(clientid: str) -> None:
    set_metadata(clientid=clientid)


def set_metadata_peername(peername: str) -> None:
    set_metadata(peername=peername)


def clear_metadata() -> None:
    _log_metadata.set({})


class MetadataFilter(logging.Filter):
    """Attach the task-scoped metadata to every record."""

    def filter(self, record: logging.LogRecord) -> bool:
        for k, v in _log_metadata.get().items():
            if not hasattr(record, k):
                setattr(record, k, v)
        record.emqx_metadata = _log_metadata.get()
        return True


_STD_ATTRS = frozenset(vars(logging.makeLogRecord({})) )


class JsonFormatter(logging.Formatter):
    """One JSON object per line: time/level/msg + metadata + extras
    (emqx_logger_jsonfmt.erl best_effort_json)."""

    def format(self, record: logging.LogRecord) -> str:
        out: dict[str, Any] = {
            "time": int(record.created * 1_000_000),    # µs like the ref
            "level": record.levelname.lower(),
            "msg": record.getMessage(),
            "logger": record.name,
        }
        for k, v in vars(record).items():
            if k in _STD_ATTRS or k in ("emqx_metadata", "message"):
                continue
            out[k] = v
        if record.exc_info:
            out["exception"] = self.formatException(record.exc_info)
        try:
            return json.dumps(out, default=_best_effort)
        except (TypeError, ValueError):
            return json.dumps({"time": out["time"], "level": out["level"],
                               "msg": str(out.get("msg"))})


def _best_effort(v: Any) -> str:
    if isinstance(v, bytes):
        return v.decode("utf-8", "replace")
    return repr(v)


class TextFormatter(logging.Formatter):
    """`2021-… [level] clientid@peername: msg` (emqx_logger_textfmt)."""

    def format(self, record: logging.LogRecord) -> str:
        ts = time.strftime("%Y-%m-%dT%H:%M:%S",
                           time.localtime(record.created))
        md = getattr(record, "emqx_metadata", None) or {}
        who = ""
        if md.get("clientid") or md.get("peername"):
            who = (f" {md.get('clientid', '')}"
                   f"@{md.get('peername', '')}:")
        base = (f"{ts}.{int(record.msecs):03d} "
                f"[{record.levelname.lower()}]{who} {record.getMessage()}")
        if record.exc_info:
            base += "\n" + self.formatException(record.exc_info)
        return base


def setup(level: int = logging.INFO, fmt: str = "text",
          stream=None) -> logging.Handler:
    """Install a root handler for the emqx_tpu namespace."""
    handler = logging.StreamHandler(stream)
    handler.setFormatter(JsonFormatter() if fmt == "json"
                         else TextFormatter())
    handler.addFilter(MetadataFilter())
    root = logging.getLogger("emqx_tpu")
    root.addHandler(handler)
    root.setLevel(level)
    # this handler is the namespace's sink: without this, records also
    # propagate to any root handler and print twice
    root.propagate = False
    return handler


_configured = False


def setup_from_config(conf: dict) -> Optional[logging.Handler]:
    """Boot-time wiring from the `log` config block (node.py calls this;
    idempotent per process so test fixtures creating many Nodes don't
    stack handlers)."""
    global _configured
    if _configured or not (conf or {}).get("enable", False):
        return None
    _configured = True
    level = getattr(logging, str(conf.get("level", "warning")).upper(),
                    logging.WARNING)
    return setup(level=level, fmt=conf.get("formatter", "text"))
