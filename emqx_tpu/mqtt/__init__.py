"""MQTT wire protocol: packet model, v3.1/v3.1.1/v5 codec, properties.

Parity targets in the reference: emqx_frame.erl (streaming parse/serialize),
emqx_packet.erl (packet helpers), emqx_mqtt_props.erl (v5 property tables),
emqx_reason_codes.erl (reason codes).
"""

from emqx_tpu.mqtt.constants import *  # noqa: F401,F403
from emqx_tpu.mqtt.packet import *  # noqa: F401,F403
from emqx_tpu.mqtt.frame import FrameParser, serialize, FrameError  # noqa: F401
