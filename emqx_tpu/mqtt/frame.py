"""Streaming MQTT wire codec for v3.1 / v3.1.1 / v5.

`FrameParser` is an incremental parser: feed() raw socket bytes, get back
complete packets, with partial frames buffered across TCP segment boundaries.
`serialize()` is the inverse. Pure Python, transport-agnostic.

Parity: reference emqx_frame.erl (streaming varint remaining-length across
segments :123-139, per-version property encoding, strict-mode validation) and
emqx_mqtt_props.erl property tables. Unlike the reference's continuation-
closure design, buffering a partial frame and re-parsing is equivalent
behavior and simpler in Python.
"""

from __future__ import annotations

import struct
import time
from typing import Optional

from emqx_tpu.mqtt import constants as C
from emqx_tpu.mqtt.packet import (
    Auth, Connack, Connect, Disconnect, Packet, Pingreq, Pingresp, Puback,
    Pubcomp, Publish, Pubrec, Pubrel, SubOpts, Subscribe, Suback, Unsuback,
    Unsubscribe, Will,
)

__all__ = ["FrameParser", "PublishBurst", "serialize", "FrameError"]


class FrameError(Exception):
    """Malformed or protocol-violating frame.

    `code` is a stable string ('malformed_packet', 'frame_too_large',
    'invalid_qos', ...) usable to pick a DISCONNECT reason code.
    """

    def __init__(self, code: str, detail: str = ""):
        super().__init__(f"{code}{': ' + detail if detail else ''}")
        self.code = code
        self.detail = detail


# ---------------------------------------------------------------------------
# primitive readers/writers
# ---------------------------------------------------------------------------

def _read_u16(buf: bytes, off: int) -> tuple[int, int]:
    if off + 2 > len(buf):
        raise FrameError("malformed_packet", "truncated u16")
    return struct.unpack_from(">H", buf, off)[0], off + 2


def _read_u32(buf: bytes, off: int) -> tuple[int, int]:
    if off + 4 > len(buf):
        raise FrameError("malformed_packet", "truncated u32")
    return struct.unpack_from(">I", buf, off)[0], off + 4


def _read_byte(buf: bytes, off: int) -> tuple[int, int]:
    if off >= len(buf):
        raise FrameError("malformed_packet", "truncated byte")
    return buf[off], off + 1


def _read_varint(buf: bytes, off: int) -> tuple[int, int]:
    """Variable byte integer, max 4 bytes (up to 268435455)."""
    mult, val, n = 1, 0, 0
    while True:
        if off >= len(buf):
            raise FrameError("malformed_packet", "truncated varint")
        b = buf[off]
        off += 1
        val += (b & 0x7F) * mult
        n += 1
        if not (b & 0x80):
            return val, off
        if n >= 4:
            raise FrameError("malformed_packet", "varint too long")
        mult <<= 7


def _read_bin(buf: bytes, off: int) -> tuple[bytes, int]:
    ln, off = _read_u16(buf, off)
    if off + ln > len(buf):
        raise FrameError("malformed_packet", "truncated binary")
    return buf[off:off + ln], off + ln


def _read_utf8(buf: bytes, off: int) -> tuple[str, int]:
    raw, off = _read_bin(buf, off)
    try:
        return raw.decode("utf-8"), off
    except UnicodeDecodeError as e:
        raise FrameError("utf8_string_invalid", str(e))


def _w_varint(val: int) -> bytes:
    if val < 0 or val > C.MAX_PACKET_SIZE:
        raise FrameError("malformed_packet", f"varint out of range: {val}")
    out = bytearray()
    while True:
        b = val & 0x7F
        val >>= 7
        if val:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _w_bin(data: bytes) -> bytes:
    if len(data) > 0xFFFF:
        raise FrameError("malformed_packet", "binary too long")
    return struct.pack(">H", len(data)) + data


def _w_utf8(s: str) -> bytes:
    return _w_bin(s.encode("utf-8"))


# ---------------------------------------------------------------------------
# v5 properties
# ---------------------------------------------------------------------------

def _parse_properties(buf: bytes, off: int) -> tuple[dict, int]:
    plen, off = _read_varint(buf, off)
    end = off + plen
    if end > len(buf):
        raise FrameError("malformed_packet", "truncated properties")
    props, _ = _parse_props_body(buf, off, end)
    return props, end


def _parse_props_body(buf: bytes, off: int, end: int) -> tuple[dict, int]:
    """Parse property CONTENT between off and end (the span after the
    length varint). Split out of _parse_properties so the columnar
    ingress path — which gets the span boundaries from the native
    decode — parses property bytes with the exact same rules."""
    props: dict = {}
    while off < end:
        pid, off = _read_byte(buf, off)
        spec = C.PROPERTIES.get(pid)
        if spec is None:
            raise FrameError("malformed_packet", f"unknown property id 0x{pid:02x}")
        name, wtype = spec
        if wtype == "byte":
            val, off = _read_byte(buf, off)
        elif wtype == "u16":
            val, off = _read_u16(buf, off)
        elif wtype == "u32":
            val, off = _read_u32(buf, off)
        elif wtype == "varint":
            val, off = _read_varint(buf, off)
        elif wtype == "binary":
            val, off = _read_bin(buf, off)
        elif wtype == "utf8":
            val, off = _read_utf8(buf, off)
        else:  # utf8_pair
            k, off = _read_utf8(buf, off)
            v, off = _read_utf8(buf, off)
            val = (k, v)
        if name == "user_property":
            props.setdefault(name, []).append(val)
        elif name == "subscription_identifier":
            props.setdefault(name, []).append(val)
        elif name in props:
            raise FrameError("protocol_error", f"duplicate property {name}")
        else:
            props[name] = val
    if off != end:
        raise FrameError("malformed_packet", "property length mismatch")
    return props, off


def _serialize_properties(props: Optional[dict]) -> bytes:
    body = bytearray()
    for name, val in (props or {}).items():
        pid = C.PROPERTY_IDS_BY_NAME.get(name)
        if pid is None:
            raise FrameError("malformed_packet", f"unknown property {name!r}")
        wtype = C.PROPERTIES[pid][1]
        multi = name in ("user_property", "subscription_identifier")
        vals = val if (multi and isinstance(val, list)) else [val]
        try:
            for v in vals:
                body.append(pid)
                if wtype == "byte":
                    body.append(int(v) & 0xFF)
                elif wtype == "u16":
                    body += struct.pack(">H", v)
                elif wtype == "u32":
                    body += struct.pack(">I", v)
                elif wtype == "varint":
                    body += _w_varint(v)
                elif wtype == "binary":
                    body += _w_bin(bytes(v))
                elif wtype == "utf8":
                    body += _w_utf8(v)
                else:  # utf8_pair
                    k, vv = v
                    body += _w_utf8(k) + _w_utf8(vv)
        except (struct.error, TypeError, ValueError) as e:
            raise FrameError("malformed_packet", f"bad value for property {name!r}: {e}")
    return _w_varint(len(body)) + bytes(body)


# ---------------------------------------------------------------------------
# parser
# ---------------------------------------------------------------------------

_FLAG_RULES = {
    C.PUBREL: 0x2, C.SUBSCRIBE: 0x2, C.UNSUBSCRIBE: 0x2,
    C.CONNECT: 0x0, C.CONNACK: 0x0, C.PUBACK: 0x0, C.PUBREC: 0x0,
    C.PUBCOMP: 0x0, C.SUBACK: 0x0, C.UNSUBACK: 0x0, C.PINGREQ: 0x0,
    C.PINGRESP: 0x0, C.DISCONNECT: 0x0, C.AUTH: 0x0,
}


class PublishBurst:
    """One contiguous run of columnar-decoded PUBLISH frames from a
    single read burst (ISSUE 11): parallel per-row lists — topic str
    (deduplicated within the burst), payload bytes (sliced once from
    the read buffer), qos/retain/dup, packet id (None at qos 0) and the
    parsed v5 properties dict ({} when absent). Rides from
    FrameParser.feed_columnar through Connection to
    Channel.handle_publish_burst without per-frame Packet objects."""

    __slots__ = ("topics", "payloads", "qos", "retain", "dup", "pids",
                 "props", "ingress_ns")

    def __init__(self):
        self.topics: list[str] = []
        self.payloads: list[bytes] = []
        self.qos: list[int] = []
        self.retain: list[bool] = []
        self.dup: list[bool] = []
        self.pids: list[Optional[int]] = []
        self.props: list[dict] = []
        # ingress stamp (ISSUE 13): ONE perf_counter_ns read at frame
        # decode covers every row of the burst — per-row attribution at
        # burst-level clock cost; the per-packet fallback stamps each
        # Publish the same way, so the A/B ingress twins stay comparable
        self.ingress_ns: int = 0

    def __len__(self) -> int:
        return len(self.topics)


class FrameParser:
    """Incremental MQTT frame parser.

    version: None on a fresh server-side connection — inferred from CONNECT;
    set explicitly for client-side parsing of server packets.
    """

    def __init__(self, version: Optional[int] = None, max_size: int = C.MAX_PACKET_SIZE,
                 strict: bool = True):
        self.version = version
        self.max_size = max_size
        self.strict = strict
        self._buf = bytearray()

    BURST_SCAN_MIN = 4096   # buffer size where the native scan pays off

    def feed(self, data: bytes) -> list[Packet]:
        """Append raw bytes; return all complete packets now parseable."""
        self._buf += data
        out: list[Packet] = []
        if len(self._buf) >= self.BURST_SCAN_MIN:
            fast = self._feed_burst()
            if fast is not None:
                out.extend(fast)
        # the incremental loop also drains any frames past the burst
        # scan's max_frames cap — nothing complete may be left buffered
        while True:
            pkt, consumed = self._try_parse_one()
            if pkt is None:
                break
            del self._buf[:consumed]
            out.append(pkt)
        if out:
            # ingress stamp (ISSUE 13): one clock read per feed covers
            # every PUBLISH decoded from this read — the latency
            # observatory's ingress→routed/delivered clock starts here
            ns = time.perf_counter_ns()
            for p in out:
                if type(p) is Publish:
                    p.ingress_ns = ns
        return out

    def _feed_burst(self) -> Optional[list[Packet]]:
        """Native boundary scan for read bursts: split the whole buffer in
        one pass and drop the consumed prefix with one delete (the
        {active,N} batch path; repeated per-frame prefix deletes are
        quadratic on large bursts). The buffer is scanned and parsed IN
        PLACE (buffer-protocol views all the way down): a burst costs one
        prefix delete plus one body extraction per frame — the old path
        copied the whole buffer into the scan and then each whole frame
        again."""
        from emqx_tpu import native
        try:
            frames, consumed = native.frame_scan(
                self._buf, max_frames=4096,
                max_frame_size=self.max_size or 0)
        except native.FrameScanError:
            return None   # let the strict parser raise its precise error
        if not frames:
            return []
        out = []
        mv = memoryview(self._buf)
        try:
            for off, length in frames:
                out.append(self._parse_frame(mv[off:off + length]))
        finally:
            mv.release()   # a live view blocks the bytearray delete
        del self._buf[:consumed]
        return out

    def _parse_frame(self, frame) -> Packet:
        """Parse one complete frame (boundaries already validated by the
        scan). Accepts bytes or a memoryview into the read buffer — only
        the BODY is materialized (the payload must outlive the buffer's
        prefix delete); the fixed header is read through the view."""
        if len(frame) < 2:
            raise FrameError("malformed_packet", "bad frame boundary")
        byte0 = frame[0]
        ptype, flags = byte0 >> 4, byte0 & 0x0F
        if ptype == C.RESERVED:
            raise FrameError("malformed_packet", "reserved packet type 0")
        rem_len, off = _read_varint(frame, 1)
        if rem_len > self.max_size:
            raise FrameError("frame_too_large",
                             f"{rem_len} > {self.max_size}")
        if off + rem_len != len(frame):
            raise FrameError("malformed_packet", "bad frame boundary")
        body = bytes(frame[off:])
        return self._parse_packet(ptype, flags, body)

    def feed_columnar(self, data) -> list:
        """feed() for the columnar ingress path (ISSUE 11): returns an
        ORDERED list of items — Packet for frames the strict per-packet
        parser handled, PublishBurst for each contiguous run of PUBLISH
        frames decoded columnar (native mqtt_publish_decode_columnar or
        its pure-python mirror, one pass over the whole read buffer).

        Falls back to the exact per-packet path for small buffers, an
        unknown protocol version (pre-CONNECT bytes must parse AFTER the
        CONNECT fixed the version) and scan errors — so the columnar-on
        and columnar-off paths differ only in who builds the publish
        rows, never in what they contain or which error they raise."""
        self._buf += data
        if len(self._buf) < self.BURST_SCAN_MIN or self.version is None:
            return self.feed(b"")
        from emqx_tpu import native
        try:
            off, lens, consumed = native.frame_scan_np(
                self._buf, max_frames=4096,
                max_frame_size=self.max_size or 0)
        except native.FrameScanError:
            return self.feed(b"")   # strict loop raises the precise error
        if not len(off):
            return self.feed(b"")
        cols = native.publish_decode_columnar(
            self._buf, off, lens, self._v5())
        # python-int rows once (numpy scalar indexing in the hot loop
        # costs more than the decode itself)
        offs = off.tolist()
        lenl = lens.tolist()
        kind = cols["kind"].tolist()
        fl = cols["flags"].tolist()
        t_off = cols["topic_off"].tolist()
        t_len = cols["topic_len"].tolist()
        pids = cols["packet_id"].tolist()
        pr_off = cols["props_off"].tolist()
        pr_len = cols["props_len"].tolist()
        p_off = cols["payload_off"].tolist()
        p_len = cols["payload_len"].tolist()
        items: list = []
        burst: Optional[PublishBurst] = None
        topic_memo: dict = {}
        mv = memoryview(self._buf)
        try:
            for i in range(len(offs)):
                if not kind[i]:
                    # non-PUBLISH (or a PUBLISH needing its precise
                    # strict-parser error): breaks the current burst so
                    # cross-frame order is preserved end to end
                    burst = None
                    a = offs[i]
                    items.append(self._parse_frame(mv[a:a + lenl[i]]))
                    continue
                a = t_off[i]
                tb = bytes(mv[a:a + t_len[i]])
                topic = topic_memo.get(tb)
                if topic is None:
                    try:
                        topic = tb.decode("utf-8")
                    except UnicodeDecodeError as e:
                        raise FrameError("utf8_string_invalid", str(e))
                    topic_memo[tb] = topic
                props: dict = {}
                if pr_len[i]:
                    b = pr_off[i]
                    span = bytes(mv[b:b + pr_len[i]])
                    props, _ = _parse_props_body(span, 0, len(span))
                if burst is None:
                    burst = PublishBurst()
                    items.append(burst)
                f = fl[i]
                q = (f >> 1) & 0x3
                a = p_off[i]
                burst.topics.append(topic)
                burst.payloads.append(bytes(mv[a:a + p_len[i]]))
                burst.qos.append(q)
                burst.retain.append(bool(f & 0x1))
                burst.dup.append(bool(f & 0x8))
                burst.pids.append(pids[i] if q else None)
                burst.props.append(props)
        finally:
            mv.release()   # a live view blocks the bytearray delete
        del self._buf[:consumed]
        # drain frames past the scan's max_frames cap — nothing complete
        # may be left buffered (the per-packet feed's contract)
        while True:
            pkt, n = self._try_parse_one()
            if pkt is None:
                break
            del self._buf[:n]
            items.append(pkt)
        if items:
            # ingress stamp (ISSUE 13): one clock read covers the whole
            # columnar read — bursts carry it once for all their rows,
            # fallback Publish frames individually (stamp-equivalent to
            # the per-packet path by construction)
            ns = time.perf_counter_ns()
            for it in items:
                if type(it) is PublishBurst or type(it) is Publish:
                    it.ingress_ns = ns
        return items

    @property
    def pending_bytes(self) -> int:
        return len(self._buf)

    def _try_parse_one(self) -> tuple[Optional[Packet], int]:
        # fixed header fits in <=5 bytes; avoid materializing the whole buffer
        # until the frame is complete (streaming a large frame stays linear)
        head = bytes(self._buf[:5])
        if len(head) < 2:
            return None, 0
        byte0 = head[0]
        ptype, flags = byte0 >> 4, byte0 & 0x0F
        if ptype == C.RESERVED:
            raise FrameError("malformed_packet", "reserved packet type 0")
        # remaining length varint — may itself be split across segments
        try:
            rem_len, off = _read_varint(head, 1)
        except FrameError as e:
            if e.detail == "truncated varint" and len(head) < 5:
                return None, 0  # wait for more bytes
            raise
        if rem_len > self.max_size:
            raise FrameError("frame_too_large", f"{rem_len} > {self.max_size}")
        if len(self._buf) < off + rem_len:
            return None, 0
        body = bytes(self._buf[off:off + rem_len])
        pkt = self._parse_packet(ptype, flags, body)
        return pkt, off + rem_len

    # -- per-type body parsing --------------------------------------------

    def _check_flags(self, ptype: int, flags: int) -> None:
        want = _FLAG_RULES.get(ptype)
        if self.strict and want is not None and flags != want:
            raise FrameError("malformed_packet",
                             f"bad flags 0x{flags:x} for {C.PACKET_TYPE_NAMES.get(ptype)}")

    def _v5(self) -> bool:
        return self.version == C.MQTT_V5

    def _check_pid(self, pid: int) -> int:
        if self.strict and pid == 0:
            raise FrameError("malformed_packet", "packet id 0")
        return pid

    def _check_end(self, body: bytes, off: int, what: str) -> None:
        if self.strict and off != len(body):
            raise FrameError("malformed_packet", f"trailing bytes in {what}")

    def _parse_packet(self, ptype: int, flags: int, body: bytes) -> Packet:
        if ptype == C.PUBLISH:
            return self._parse_publish(flags, body)
        self._check_flags(ptype, flags)
        if ptype == C.CONNECT:
            return self._parse_connect(body)
        if ptype == C.CONNACK:
            return self._parse_connack(body)
        if ptype in (C.PUBACK, C.PUBREC, C.PUBREL, C.PUBCOMP):
            return self._parse_puback(ptype, body)
        if ptype == C.SUBSCRIBE:
            return self._parse_subscribe(body)
        if ptype == C.SUBACK:
            return self._parse_suback(body)
        if ptype == C.UNSUBSCRIBE:
            return self._parse_unsubscribe(body)
        if ptype == C.UNSUBACK:
            return self._parse_unsuback(body)
        if ptype == C.PINGREQ:
            return Pingreq()
        if ptype == C.PINGRESP:
            return Pingresp()
        if ptype == C.DISCONNECT:
            return self._parse_disconnect(body)
        if ptype == C.AUTH:
            return self._parse_auth(body)
        raise FrameError("malformed_packet", f"unknown packet type {ptype}")

    def _parse_connect(self, body: bytes) -> Connect:
        off = 0
        proto_name, off = _read_utf8(body, off)
        proto_ver, off = _read_byte(body, off)
        expected = C.PROTOCOL_NAMES.get(proto_ver)
        if expected is None or proto_name != expected:
            raise FrameError("unsupported_protocol_version",
                             f"{proto_name!r} v{proto_ver}")
        self.version = proto_ver
        cflags, off = _read_byte(body, off)
        if self.strict and (cflags & 0x01):
            raise FrameError("malformed_packet", "CONNECT reserved flag set")
        clean_start = bool(cflags & 0x02)
        will_flag = bool(cflags & 0x04)
        will_qos = (cflags >> 3) & 0x3
        will_retain = bool(cflags & 0x20)
        has_password = bool(cflags & 0x40)
        has_username = bool(cflags & 0x80)
        if will_qos > C.QOS_2 or (not will_flag and will_qos):
            raise FrameError("malformed_packet", "bad will qos")
        keepalive, off = _read_u16(body, off)
        props: dict = {}
        if self._v5():
            props, off = _parse_properties(body, off)
        clientid, off = _read_utf8(body, off)
        will = None
        if will_flag:
            wprops: dict = {}
            if self._v5():
                wprops, off = _parse_properties(body, off)
            wtopic, off = _read_utf8(body, off)
            wpayload, off = _read_bin(body, off)
            will = Will(topic=wtopic, payload=wpayload, qos=will_qos,
                        retain=will_retain, properties=wprops)
        username = password = None
        if has_username:
            username, off = _read_utf8(body, off)
        if has_password:
            password, off = _read_bin(body, off)
        if self.strict and off != len(body):
            raise FrameError("malformed_packet", "trailing bytes in CONNECT")
        return Connect(proto_name=proto_name, proto_ver=proto_ver,
                       clean_start=clean_start, keepalive=keepalive,
                       clientid=clientid, will=will, username=username,
                       password=password, properties=props)

    def _parse_connack(self, body: bytes) -> Connack:
        off = 0
        ack, off = _read_byte(body, off)
        rc, off = _read_byte(body, off)
        props: dict = {}
        if self._v5():
            props, off = _parse_properties(body, off)
        self._check_end(body, off, "CONNACK")
        return Connack(session_present=bool(ack & 1), reason_code=rc,
                       properties=props)

    def _parse_publish(self, flags: int, body: bytes) -> Publish:
        dup = bool(flags & 0x8)
        qos = (flags >> 1) & 0x3
        retain = bool(flags & 0x1)
        if qos > C.QOS_2:
            raise FrameError("invalid_qos", "PUBLISH qos 3")
        off = 0
        topic, off = _read_utf8(body, off)
        packet_id = None
        if qos > C.QOS_0:
            packet_id, off = _read_u16(body, off)
            if packet_id == 0:
                raise FrameError("malformed_packet", "packet id 0")
        props: dict = {}
        if self._v5():
            props, off = _parse_properties(body, off)
        return Publish(topic=topic, payload=body[off:], qos=qos, retain=retain,
                       dup=dup, packet_id=packet_id, properties=props)

    def _parse_puback(self, ptype: int, body: bytes) -> Packet:
        cls = {C.PUBACK: Puback, C.PUBREC: Pubrec, C.PUBREL: Pubrel,
               C.PUBCOMP: Pubcomp}[ptype]
        packet_id, off = _read_u16(body, 0)
        self._check_pid(packet_id)
        rc, props = C.RC_SUCCESS, {}
        if self._v5() and len(body) > off:
            rc, off = _read_byte(body, off)
            if len(body) > off:
                props, off = _parse_properties(body, off)
        self._check_end(body, off, "ack packet")
        return cls(packet_id=packet_id, reason_code=rc, properties=props)

    def _parse_subscribe(self, body: bytes) -> Subscribe:
        packet_id, off = _read_u16(body, 0)
        self._check_pid(packet_id)
        props: dict = {}
        if self._v5():
            props, off = _parse_properties(body, off)
        filters = []
        while off < len(body):
            filt, off = _read_utf8(body, off)
            ob, off = _read_byte(body, off)
            if self.strict and (ob & (0xC0 if self._v5() else 0xFC)):
                raise FrameError("malformed_packet", "reserved subopts bits set")
            opts = SubOpts.from_byte(ob)
            if opts.qos > C.QOS_2:
                raise FrameError("invalid_qos", "subscribe qos 3")
            filters.append((filt, opts))
        if not filters:
            raise FrameError("protocol_error", "SUBSCRIBE with no filters")
        return Subscribe(packet_id=packet_id, filters=filters, properties=props)

    def _parse_suback(self, body: bytes) -> Suback:
        packet_id, off = _read_u16(body, 0)
        self._check_pid(packet_id)
        props: dict = {}
        if self._v5():
            props, off = _parse_properties(body, off)
        return Suback(packet_id=packet_id, reason_codes=list(body[off:]),
                      properties=props)

    def _parse_unsubscribe(self, body: bytes) -> Unsubscribe:
        packet_id, off = _read_u16(body, 0)
        self._check_pid(packet_id)
        props: dict = {}
        if self._v5():
            props, off = _parse_properties(body, off)
        filters = []
        while off < len(body):
            filt, off = _read_utf8(body, off)
            filters.append(filt)
        if not filters:
            raise FrameError("protocol_error", "UNSUBSCRIBE with no filters")
        return Unsubscribe(packet_id=packet_id, filters=filters, properties=props)

    def _parse_unsuback(self, body: bytes) -> Unsuback:
        packet_id, off = _read_u16(body, 0)
        self._check_pid(packet_id)
        props: dict = {}
        codes: list = []
        if self._v5():
            props, off = _parse_properties(body, off)
            codes = list(body[off:])
        return Unsuback(packet_id=packet_id, reason_codes=codes, properties=props)

    def _parse_disconnect(self, body: bytes) -> Disconnect:
        rc, props = C.RC_NORMAL_DISCONNECTION, {}
        if self._v5() and body:
            rc, off = _read_byte(body, 0)
            if len(body) > off:
                props, off = _parse_properties(body, off)
            self._check_end(body, off, "DISCONNECT")
        return Disconnect(reason_code=rc, properties=props)

    def _parse_auth(self, body: bytes) -> Auth:
        if not self._v5():
            raise FrameError("malformed_packet", "AUTH before MQTT 5")
        rc, props = C.RC_SUCCESS, {}
        if body:
            rc, off = _read_byte(body, 0)
            if len(body) > off:
                props, off = _parse_properties(body, off)
            self._check_end(body, off, "AUTH")
        return Auth(reason_code=rc, properties=props)


# ---------------------------------------------------------------------------
# serializer
# ---------------------------------------------------------------------------

def serialize(pkt: Packet, version: int = C.MQTT_V4) -> bytes:
    """Serialize a packet for the given protocol version."""
    v5 = version == C.MQTT_V5
    t = pkt.type
    flags = 0
    if t == C.PUBLISH:
        flags = ((0x8 if pkt.dup else 0) | ((pkt.qos & 0x3) << 1)
                 | (0x1 if pkt.retain else 0))
    elif t in (C.PUBREL, C.SUBSCRIBE, C.UNSUBSCRIBE):
        flags = 0x2
    body = _serialize_body(pkt, version, v5)
    if len(body) > C.MAX_PACKET_SIZE:
        raise FrameError("frame_too_large", f"body {len(body)}")
    return bytes([t << 4 | flags]) + _w_varint(len(body)) + body


def _serialize_body(pkt: Packet, version: int, v5: bool) -> bytes:
    t = pkt.type
    if t == C.CONNECT:
        return _serialize_connect(pkt)
    if t == C.CONNACK:
        out = bytes([1 if pkt.session_present else 0,
                     pkt.reason_code if v5 else C.rc_to_connack_v3(pkt.reason_code)])
        if v5:
            out += _serialize_properties(pkt.properties)
        return out
    if t == C.PUBLISH:
        out = _w_utf8(pkt.topic)
        if pkt.qos > C.QOS_0:
            if not pkt.packet_id:
                raise FrameError("malformed_packet", "qos>0 publish without packet id")
            out += struct.pack(">H", pkt.packet_id)
        if v5:
            out += _serialize_properties(pkt.properties)
        return out + bytes(pkt.payload)
    if t in (C.PUBACK, C.PUBREC, C.PUBREL, C.PUBCOMP):
        out = struct.pack(">H", pkt.packet_id)
        if v5 and (pkt.reason_code != C.RC_SUCCESS or pkt.properties):
            out += bytes([pkt.reason_code])
            if pkt.properties:
                out += _serialize_properties(pkt.properties)
        return out
    if t == C.SUBSCRIBE:
        out = struct.pack(">H", pkt.packet_id)
        if v5:
            out += _serialize_properties(pkt.properties)
        for filt, opts in pkt.filters:
            ob = opts.to_byte() if v5 else (opts.qos & 0x3)
            out += _w_utf8(filt) + bytes([ob])
        return out
    if t == C.SUBACK:
        out = struct.pack(">H", pkt.packet_id)
        if v5:
            out += _serialize_properties(pkt.properties)
        return out + bytes(pkt.reason_codes)
    if t == C.UNSUBSCRIBE:
        out = struct.pack(">H", pkt.packet_id)
        if v5:
            out += _serialize_properties(pkt.properties)
        for filt in pkt.filters:
            out += _w_utf8(filt)
        return out
    if t == C.UNSUBACK:
        out = struct.pack(">H", pkt.packet_id)
        if v5:
            out += _serialize_properties(pkt.properties)
            out += bytes(pkt.reason_codes)
        return out
    if t in (C.PINGREQ, C.PINGRESP):
        return b""
    if t == C.DISCONNECT:
        if not v5:
            return b""
        if pkt.reason_code == C.RC_NORMAL_DISCONNECTION and not pkt.properties:
            return b""
        out = bytes([pkt.reason_code])
        if pkt.properties:
            out += _serialize_properties(pkt.properties)
        return out
    if t == C.AUTH:
        if pkt.reason_code == C.RC_SUCCESS and not pkt.properties:
            return b""
        out = bytes([pkt.reason_code])
        if pkt.properties:
            out += _serialize_properties(pkt.properties)
        return out
    raise FrameError("malformed_packet", f"cannot serialize type {t}")


def _serialize_connect(pkt: Connect) -> bytes:
    v5 = pkt.proto_ver == C.MQTT_V5
    cflags = 0
    if pkt.clean_start:
        cflags |= 0x02
    if pkt.will is not None:
        cflags |= 0x04 | ((pkt.will.qos & 0x3) << 3)
        if pkt.will.retain:
            cflags |= 0x20
    if pkt.password is not None:
        cflags |= 0x40
    if pkt.username is not None:
        cflags |= 0x80
    out = _w_utf8(C.PROTOCOL_NAMES[pkt.proto_ver])
    out += bytes([pkt.proto_ver, cflags]) + struct.pack(">H", pkt.keepalive)
    if v5:
        out += _serialize_properties(pkt.properties)
    out += _w_utf8(pkt.clientid)
    if pkt.will is not None:
        if v5:
            out += _serialize_properties(pkt.will.properties)
        out += _w_utf8(pkt.will.topic) + _w_bin(pkt.will.payload)
    if pkt.username is not None:
        out += _w_utf8(pkt.username)
    if pkt.password is not None:
        out += _w_bin(pkt.password)
    return out
