"""MQTT packet model: one dataclass per control packet type.

Properties are plain dicts keyed by snake_case names from
`emqx_tpu.mqtt.constants.PROPERTIES`; `user_property` holds a list of
(key, value) string pairs; `subscription_identifier` may repeat and holds a
list of ints in parsed packets.

Parity: reference emqx_packet.erl / include/emqx_mqtt.hrl record shapes.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from emqx_tpu.mqtt import constants as C

__all__ = [
    "Packet", "Connect", "Connack", "Publish", "Puback", "Pubrec", "Pubrel",
    "Pubcomp", "Subscribe", "Suback", "Unsubscribe", "Unsuback", "Pingreq",
    "Pingresp", "Disconnect", "Auth", "SubOpts", "Will",
]


@dataclass
class SubOpts:
    """Per-filter subscription options (v5; v3 uses qos only).

    rh: retain handling 0|1|2, rap: retain-as-published, nl: no-local.
    """
    qos: int = 0
    nl: int = 0
    rap: int = 0
    rh: int = 0

    def to_byte(self) -> int:
        return (self.qos & 0x3) | (self.nl << 2) | (self.rap << 3) | ((self.rh & 0x3) << 4)

    @classmethod
    def from_byte(cls, b: int) -> "SubOpts":
        return cls(qos=b & 0x3, nl=(b >> 2) & 1, rap=(b >> 3) & 1, rh=(b >> 4) & 0x3)


@dataclass
class Will:
    topic: str = ""
    payload: bytes = b""
    qos: int = 0
    retain: bool = False
    properties: dict = field(default_factory=dict)


class Packet:
    """Base class; `type` is the MQTT control packet type number."""
    type: int = C.RESERVED

    @property
    def type_name(self) -> str:
        return C.PACKET_TYPE_NAMES.get(self.type, f"UNKNOWN({self.type})")


@dataclass
class Connect(Packet):
    proto_name: str = "MQTT"
    proto_ver: int = C.MQTT_V4
    clean_start: bool = True
    keepalive: int = 0
    clientid: str = ""
    will: Optional[Will] = None
    username: Optional[str] = None
    password: Optional[bytes] = None
    properties: dict = field(default_factory=dict)
    type = C.CONNECT


@dataclass
class Connack(Packet):
    session_present: bool = False
    reason_code: int = C.RC_SUCCESS
    properties: dict = field(default_factory=dict)
    type = C.CONNACK


@dataclass
class Publish(Packet):
    topic: str = ""
    payload: bytes = b""
    qos: int = 0
    retain: bool = False
    dup: bool = False
    packet_id: Optional[int] = None
    properties: dict = field(default_factory=dict)
    type = C.PUBLISH
    # ingress stamp (ISSUE 13): perf_counter_ns at frame decode, set by
    # FrameParser on inbound PUBLISHes. A plain class attribute (not a
    # dataclass field): every packet answers 0 with no per-instance
    # cost, equality/repr semantics untouched.
    ingress_ns = 0


@dataclass
class _PubAckBase(Packet):
    packet_id: int = 0
    reason_code: int = C.RC_SUCCESS
    properties: dict = field(default_factory=dict)


@dataclass
class Puback(_PubAckBase):
    type = C.PUBACK


@dataclass
class Pubrec(_PubAckBase):
    type = C.PUBREC


@dataclass
class Pubrel(_PubAckBase):
    type = C.PUBREL


@dataclass
class Pubcomp(_PubAckBase):
    type = C.PUBCOMP


@dataclass
class Subscribe(Packet):
    packet_id: int = 0
    # list of (topic_filter, SubOpts)
    filters: list = field(default_factory=list)
    properties: dict = field(default_factory=dict)
    type = C.SUBSCRIBE


@dataclass
class Suback(Packet):
    packet_id: int = 0
    reason_codes: list = field(default_factory=list)
    properties: dict = field(default_factory=dict)
    type = C.SUBACK


@dataclass
class Unsubscribe(Packet):
    packet_id: int = 0
    filters: list = field(default_factory=list)
    properties: dict = field(default_factory=dict)
    type = C.UNSUBSCRIBE


@dataclass
class Unsuback(Packet):
    packet_id: int = 0
    reason_codes: list = field(default_factory=list)
    properties: dict = field(default_factory=dict)
    type = C.UNSUBACK


@dataclass
class Pingreq(Packet):
    type = C.PINGREQ


@dataclass
class Pingresp(Packet):
    type = C.PINGRESP


@dataclass
class Disconnect(Packet):
    reason_code: int = C.RC_NORMAL_DISCONNECTION
    properties: dict = field(default_factory=dict)
    type = C.DISCONNECT


@dataclass
class Auth(Packet):
    reason_code: int = C.RC_SUCCESS
    properties: dict = field(default_factory=dict)
    type = C.AUTH
