"""QUIC v1 frame codec (RFC 9000 §19) — the subset MQTT-over-QUIC uses.

PADDING, PING, ACK, CRYPTO, STREAM (all offset/len/fin variants),
MAX_DATA/MAX_STREAM_DATA/MAX_STREAMS, CONNECTION_CLOSE (transport + app),
HANDSHAKE_DONE, NEW_CONNECTION_ID (parsed + ignored), RESET_STREAM,
STOP_SENDING.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

from emqx_tpu.quic.packet import dec_varint, enc_varint

FT_PADDING = 0x00
FT_PING = 0x01
FT_ACK = 0x02
FT_ACK_ECN = 0x03
FT_RESET_STREAM = 0x04
FT_STOP_SENDING = 0x05
FT_CRYPTO = 0x06
FT_NEW_TOKEN = 0x07
FT_STREAM = 0x08          # ..0x0F with OFF/LEN/FIN bits
FT_MAX_DATA = 0x10
FT_MAX_STREAM_DATA = 0x11
FT_MAX_STREAMS_BIDI = 0x12
FT_MAX_STREAMS_UNI = 0x13
FT_NEW_CONNECTION_ID = 0x18
FT_RETIRE_CONNECTION_ID = 0x19
FT_CONNECTION_CLOSE = 0x1C
FT_CONNECTION_CLOSE_APP = 0x1D
FT_HANDSHAKE_DONE = 0x1E


class Crypto(NamedTuple):
    offset: int
    data: bytes


class Stream(NamedTuple):
    stream_id: int
    offset: int
    data: bytes
    fin: bool


class Ack(NamedTuple):
    largest: int
    delay: int
    ranges: list[tuple[int, int]]    # [(lo, hi)] descending


class Close(NamedTuple):
    error_code: int
    frame_type: Optional[int]        # None for app close
    reason: str


class ResetStream(NamedTuple):
    stream_id: int
    error_code: int
    final_size: int


class MaxData(NamedTuple):
    value: int


class MaxStreamData(NamedTuple):
    stream_id: int
    value: int


class HandshakeDone(NamedTuple):
    pass


class Ping(NamedTuple):
    pass


def encode_crypto(offset: int, data: bytes) -> bytes:
    return (bytes([FT_CRYPTO]) + enc_varint(offset)
            + enc_varint(len(data)) + data)


def encode_stream(stream_id: int, offset: int, data: bytes,
                  fin: bool = False) -> bytes:
    ftype = FT_STREAM | 0x02 | (0x04 if offset else 0) | (1 if fin else 0)
    out = bytes([ftype]) + enc_varint(stream_id)
    if offset:
        out += enc_varint(offset)
    return out + enc_varint(len(data)) + data


def encode_ack(largest: int, ranges: list[tuple[int, int]],
               delay: int = 0) -> bytes:
    """ranges: [(lo, hi)] sorted descending by hi; largest == ranges[0][1]."""
    lo0, hi0 = ranges[0]
    out = (bytes([FT_ACK]) + enc_varint(largest) + enc_varint(delay)
           + enc_varint(len(ranges) - 1) + enc_varint(hi0 - lo0))
    prev_lo = lo0
    for lo, hi in ranges[1:]:
        out += enc_varint(prev_lo - hi - 2) + enc_varint(hi - lo)
        prev_lo = lo
    return out


def encode_close(error_code: int, reason: str = "",
                 frame_type: int = 0, app: bool = False) -> bytes:
    r = reason.encode()
    out = bytes([FT_CONNECTION_CLOSE_APP if app else FT_CONNECTION_CLOSE])
    out += enc_varint(error_code)
    if not app:
        out += enc_varint(frame_type)
    return out + enc_varint(len(r)) + r


def encode_handshake_done() -> bytes:
    return bytes([FT_HANDSHAKE_DONE])


def encode_max_data(v: int) -> bytes:
    return bytes([FT_MAX_DATA]) + enc_varint(v)


def encode_max_stream_data(sid: int, v: int) -> bytes:
    return bytes([FT_MAX_STREAM_DATA]) + enc_varint(sid) + enc_varint(v)


class FrameError(Exception):
    pass


def parse_frames(payload: bytes) -> list:
    """-> list of frame tuples (PADDING/PING folded away except one Ping
    marker so the caller knows to ack)."""
    out: list = []
    pos = 0
    n = len(payload)
    saw_ping = False
    while pos < n:
        ftype = payload[pos]
        pos += 1
        if ftype == FT_PADDING:
            continue
        if ftype == FT_PING:
            saw_ping = True
            continue
        if ftype in (FT_ACK, FT_ACK_ECN):
            largest, pos = dec_varint(payload, pos)
            delay, pos = dec_varint(payload, pos)
            count, pos = dec_varint(payload, pos)
            first, pos = dec_varint(payload, pos)
            ranges = [(largest - first, largest)]
            lo = largest - first
            for _ in range(count):
                gap, pos = dec_varint(payload, pos)
                length, pos = dec_varint(payload, pos)
                hi = lo - gap - 2
                ranges.append((hi - length, hi))
                lo = hi - length
            if ftype == FT_ACK_ECN:
                for _ in range(3):
                    _, pos = dec_varint(payload, pos)
            out.append(Ack(largest=largest, delay=delay, ranges=ranges))
        elif ftype == FT_CRYPTO:
            off, pos = dec_varint(payload, pos)
            ln, pos = dec_varint(payload, pos)
            out.append(Crypto(offset=off, data=payload[pos:pos + ln]))
            pos += ln
        elif FT_STREAM <= ftype <= FT_STREAM | 0x07:
            sid, pos = dec_varint(payload, pos)
            off = 0
            if ftype & 0x04:
                off, pos = dec_varint(payload, pos)
            if ftype & 0x02:
                ln, pos = dec_varint(payload, pos)
            else:
                ln = n - pos
            out.append(Stream(stream_id=sid, offset=off,
                              data=payload[pos:pos + ln],
                              fin=bool(ftype & 0x01)))
            pos += ln
        elif ftype == FT_RESET_STREAM:
            sid, pos = dec_varint(payload, pos)
            ec, pos = dec_varint(payload, pos)
            fs, pos = dec_varint(payload, pos)
            out.append(ResetStream(stream_id=sid, error_code=ec,
                                   final_size=fs))
        elif ftype == FT_STOP_SENDING:
            _sid, pos = dec_varint(payload, pos)
            _ec, pos = dec_varint(payload, pos)
        elif ftype == FT_MAX_DATA:
            v, pos = dec_varint(payload, pos)
            out.append(MaxData(value=v))
        elif ftype == FT_MAX_STREAM_DATA:
            sid, pos = dec_varint(payload, pos)
            v, pos = dec_varint(payload, pos)
            out.append(MaxStreamData(stream_id=sid, value=v))
        elif ftype in (FT_MAX_STREAMS_BIDI, FT_MAX_STREAMS_UNI):
            _, pos = dec_varint(payload, pos)
        elif ftype == FT_NEW_CONNECTION_ID:
            _seq, pos = dec_varint(payload, pos)
            _ret, pos = dec_varint(payload, pos)
            ln = payload[pos]
            pos += 1 + ln + 16          # cid + stateless reset token
        elif ftype == FT_RETIRE_CONNECTION_ID:
            _, pos = dec_varint(payload, pos)
        elif ftype == FT_NEW_TOKEN:
            ln, pos = dec_varint(payload, pos)
            pos += ln
        elif ftype in (FT_CONNECTION_CLOSE, FT_CONNECTION_CLOSE_APP):
            ec, pos = dec_varint(payload, pos)
            ft = None
            if ftype == FT_CONNECTION_CLOSE:
                ft, pos = dec_varint(payload, pos)
            ln, pos = dec_varint(payload, pos)
            reason = payload[pos:pos + ln].decode("utf-8", "replace")
            pos += ln
            out.append(Close(error_code=ec, frame_type=ft, reason=reason))
        elif ftype == FT_HANDSHAKE_DONE:
            out.append(HandshakeDone())
        else:
            raise FrameError(f"unknown frame type 0x{ftype:02x}")
    if saw_ping:
        out.append(Ping())
    return out
