"""Minimal TLS 1.3 (RFC 8446) handshake engine for QUIC.

Implements exactly the profile QUIC v1 needs (RFC 9001): the handshake
messages ride CRYPTO frames (no record layer), one cipher suite
(TLS_AES_128_GCM_SHA256), one group (x25519), server auth via
rsa_pss_rsae_sha256 or ecdsa_secp256r1_sha256. Both roles are
implemented (the reference's msquic provides both; the client side here
drives tests and the MQTT bridge).

The engine is sans-IO: feed_crypto(level, bytes) consumes handshake
bytes; outputs accumulate in `pending` as (level, bytes) and derived
traffic secrets in `secrets` as level -> (client_secret, server_secret).
Levels: 0 initial, 1 handshake, 2 application (1-RTT).
"""

from __future__ import annotations

import hashlib
import hmac
import os
import struct
from typing import Optional

from cryptography.hazmat.primitives import hashes, serialization
from cryptography.hazmat.primitives.asymmetric import ec, padding, rsa
from cryptography.hazmat.primitives.asymmetric.x25519 import (
    X25519PrivateKey, X25519PublicKey)

INITIAL, HANDSHAKE, APPLICATION = 0, 1, 2

TLS_AES_128_GCM_SHA256 = 0x1301
GROUP_X25519 = 0x001D
SIG_RSA_PSS_SHA256 = 0x0804
SIG_ECDSA_P256_SHA256 = 0x0403

EXT_SNI = 0
EXT_SUPPORTED_GROUPS = 10
EXT_SIG_ALGS = 13
EXT_ALPN = 16
EXT_SUPPORTED_VERSIONS = 43
EXT_PSK_MODES = 45
EXT_KEY_SHARE = 51
EXT_QUIC_TP = 0x39

HT_CLIENT_HELLO = 1
HT_SERVER_HELLO = 2
HT_ENCRYPTED_EXTENSIONS = 8
HT_CERTIFICATE = 11
HT_CERTIFICATE_VERIFY = 15
HT_FINISHED = 20


def _system_cafile() -> Optional[str]:
    """Best-effort system trust bundle path (OpenSSL default paths plus
    the usual distro locations)."""
    import os
    import ssl
    paths = ssl.get_default_verify_paths()
    for p in (paths.cafile, paths.openssl_cafile,
              "/etc/ssl/certs/ca-certificates.crt",
              "/etc/pki/tls/certs/ca-bundle.crt",
              "/etc/ssl/cert.pem"):
        if p and os.path.isfile(p):
            return p
    return None


class TlsError(Exception):
    def __init__(self, msg: str, alert: int = 40):   # handshake_failure
        self.alert = alert
        super().__init__(msg)


# ---------------------------------------------------------------------------
# HKDF (RFC 5869 + RFC 8446 §7.1), SHA-256 only
# ---------------------------------------------------------------------------

def hkdf_extract(salt: bytes, ikm: bytes) -> bytes:
    return hmac.new(salt or b"\x00" * 32, ikm, hashlib.sha256).digest()


def hkdf_expand(prk: bytes, info: bytes, length: int) -> bytes:
    out, block, i = b"", b"", 1
    while len(out) < length:
        block = hmac.new(prk, block + info + bytes([i]),
                         hashlib.sha256).digest()
        out += block
        i += 1
    return out[:length]


def hkdf_expand_label(secret: bytes, label: str, context: bytes,
                      length: int) -> bytes:
    lab = b"tls13 " + label.encode()
    info = (struct.pack(">H", length) + bytes([len(lab)]) + lab
            + bytes([len(context)]) + context)
    return hkdf_expand(secret, info, length)


def derive_secret(secret: bytes, label: str, transcript: bytes) -> bytes:
    return hkdf_expand_label(secret, label, transcript, 32)


_EMPTY_HASH = hashlib.sha256(b"").digest()


# ---------------------------------------------------------------------------
# wire helpers
# ---------------------------------------------------------------------------

def _v8(b: bytes) -> bytes:
    return bytes([len(b)]) + b


def _v16(b: bytes) -> bytes:
    return struct.pack(">H", len(b)) + b


def _v24(b: bytes) -> bytes:
    return len(b).to_bytes(3, "big") + b


def _hs_msg(htype: int, body: bytes) -> bytes:
    return bytes([htype]) + _v24(body)


def _ext(etype: int, body: bytes) -> bytes:
    return struct.pack(">HH", etype, len(body)) + body


def _parse_exts(data: bytes) -> dict[int, bytes]:
    out: dict[int, bytes] = {}
    pos = 0
    while pos + 4 <= len(data):
        et, ln = struct.unpack_from(">HH", data, pos)
        out[et] = data[pos + 4:pos + 4 + ln]
        pos += 4 + ln
    return out


class _HsBuffer:
    """Reassembles the CRYPTO byte stream into handshake messages."""

    def __init__(self):
        self.buf = b""

    def feed(self, data: bytes) -> list[tuple[int, bytes, bytes]]:
        self.buf += data
        out = []
        while len(self.buf) >= 4:
            htype = self.buf[0]
            ln = int.from_bytes(self.buf[1:4], "big")
            if len(self.buf) < 4 + ln:
                break
            raw = self.buf[:4 + ln]
            out.append((htype, self.buf[4:4 + ln], raw))
            self.buf = self.buf[4 + ln:]
        return out


class _Base:
    def __init__(self):
        self.pending: list[tuple[int, bytes]] = []
        self.secrets: dict[int, tuple[bytes, bytes]] = {}
        self.transcript = hashlib.sha256()
        self.complete = False
        self.alpn: Optional[str] = None
        self.peer_transport_params: Optional[bytes] = None
        self._buffers = {INITIAL: _HsBuffer(), HANDSHAKE: _HsBuffer(),
                         APPLICATION: _HsBuffer()}
        self._hs_secret = b""
        self._master = b""
        self._client_hs = b""
        self._server_hs = b""

    def _send(self, level: int, raw: bytes) -> None:
        self.pending.append((level, raw))

    def _th(self) -> bytes:
        return self.transcript.copy().digest()

    def _derive_hs(self, shared: bytes) -> None:
        early = hkdf_extract(b"", b"\x00" * 32)
        derived = derive_secret(early, "derived", _EMPTY_HASH)
        self._hs_secret = hkdf_extract(derived, shared)
        th = self._th()
        self._client_hs = derive_secret(self._hs_secret, "c hs traffic", th)
        self._server_hs = derive_secret(self._hs_secret, "s hs traffic", th)
        self.secrets[HANDSHAKE] = (self._client_hs, self._server_hs)
        d2 = derive_secret(self._hs_secret, "derived", _EMPTY_HASH)
        self._master = hkdf_extract(d2, b"\x00" * 32)

    def _derive_app(self) -> None:
        th = self._th()   # transcript through server Finished
        cap = derive_secret(self._master, "c ap traffic", th)
        sap = derive_secret(self._master, "s ap traffic", th)
        self.secrets[APPLICATION] = (cap, sap)

    @staticmethod
    def _finished_mac(traffic_secret: bytes, th: bytes) -> bytes:
        fk = hkdf_expand_label(traffic_secret, "finished", b"", 32)
        return hmac.new(fk, th, hashlib.sha256).digest()

    @staticmethod
    def _cv_content(th: bytes, server: bool) -> bytes:
        role = b"server" if server else b"client"
        return (b"\x20" * 64 + b"TLS 1.3, " + role
                + b" CertificateVerify\x00" + th)


# ---------------------------------------------------------------------------
# server
# ---------------------------------------------------------------------------

class Tls13Server(_Base):
    def __init__(self, certfile: str, keyfile: str,
                 alpn_protocols: list[str],
                 transport_params: bytes):
        super().__init__()
        from cryptography import x509
        with open(certfile, "rb") as f:
            pem = f.read()
        self._certs = x509.load_pem_x509_certificates(pem)
        with open(keyfile, "rb") as f:
            self._key = serialization.load_pem_private_key(f.read(), None)
        self._alpn_offer = alpn_protocols
        self._tp = transport_params
        self._client_finished_due = False

    def feed_crypto(self, level: int, data: bytes) -> None:
        for htype, body, raw in self._buffers[level].feed(data):
            if htype == HT_CLIENT_HELLO and level == INITIAL \
                    and not self._hs_secret:
                self._on_client_hello(body, raw)
            elif htype == HT_FINISHED and level == HANDSHAKE \
                    and self._client_finished_due:
                expect = self._finished_mac(self._client_hs, self._th())
                if not hmac.compare_digest(body, expect):
                    raise TlsError("bad client Finished", 51)
                self.transcript.update(raw)
                self._client_finished_due = False
                self.complete = True
            else:
                raise TlsError(f"unexpected handshake message {htype} "
                               f"at level {level}", 10)

    def _on_client_hello(self, body: bytes, raw: bytes) -> None:
        pos = 2 + 32                                  # version + random
        sid_len = body[pos]
        session_id = body[pos + 1:pos + 1 + sid_len]
        pos += 1 + sid_len
        cs_len = struct.unpack_from(">H", body, pos)[0]
        suites = [struct.unpack_from(">H", body, pos + 2 + i)[0]
                  for i in range(0, cs_len, 2)]
        pos += 2 + cs_len
        pos += 1 + body[pos]                          # compression methods
        ext_len = struct.unpack_from(">H", body, pos)[0]
        exts = _parse_exts(body[pos + 2:pos + 2 + ext_len])

        if TLS_AES_128_GCM_SHA256 not in suites:
            raise TlsError("no common cipher suite", 71)
        sv = exts.get(EXT_SUPPORTED_VERSIONS, b"")
        if b"\x03\x04" not in sv:
            raise TlsError("TLS 1.3 not offered", 70)
        peer_pub = None
        ks = exts.get(EXT_KEY_SHARE, b"")
        if len(ks) >= 2:
            kpos = 2
            while kpos + 4 <= len(ks):
                grp, ln = struct.unpack_from(">HH", ks, kpos)
                if grp == GROUP_X25519 and ln == 32:
                    peer_pub = ks[kpos + 4:kpos + 36]
                kpos += 4 + ln
        if peer_pub is None:
            raise TlsError("no x25519 key share", 40)
        if EXT_QUIC_TP in exts:
            self.peer_transport_params = exts[EXT_QUIC_TP]
        alpn = exts.get(EXT_ALPN)
        chosen = None
        if alpn is not None and len(alpn) >= 2:
            apos = 2
            offered = []
            while apos < len(alpn):
                ln = alpn[apos]
                offered.append(alpn[apos + 1:apos + 1 + ln].decode())
                apos += 1 + ln
            for p in self._alpn_offer:
                if p in offered:
                    chosen = p
                    break
            if chosen is None:
                raise TlsError("no common ALPN protocol", 120)
        self.alpn = chosen

        self.transcript.update(raw)
        priv = X25519PrivateKey.generate()
        shared = priv.exchange(X25519PublicKey.from_public_bytes(peer_pub))
        my_pub = priv.public_key().public_bytes(
            serialization.Encoding.Raw, serialization.PublicFormat.Raw)

        sh_exts = (_ext(EXT_SUPPORTED_VERSIONS, b"\x03\x04")
                   + _ext(EXT_KEY_SHARE,
                          struct.pack(">HH", GROUP_X25519, 32) + my_pub))
        sh_body = (b"\x03\x03" + os.urandom(32) + _v8(session_id)
                   + struct.pack(">H", TLS_AES_128_GCM_SHA256) + b"\x00"
                   + _v16(sh_exts))
        sh = _hs_msg(HT_SERVER_HELLO, sh_body)
        self.transcript.update(sh)
        self._send(INITIAL, sh)
        self._derive_hs(shared)

        # EncryptedExtensions + Certificate + CertificateVerify + Finished
        ee_exts = _ext(EXT_QUIC_TP, self._tp)
        if chosen:
            ee_exts += _ext(EXT_ALPN, _v16(_v8(chosen.encode())))
        flight = _hs_msg(HT_ENCRYPTED_EXTENSIONS, _v16(ee_exts))
        self.transcript.update(flight)

        entries = b"".join(
            _v24(c.public_bytes(serialization.Encoding.DER)) + b"\x00\x00"
            for c in self._certs)
        cert = _hs_msg(HT_CERTIFICATE, b"\x00" + _v24(entries))
        self.transcript.update(cert)
        flight += cert

        content = self._cv_content(self._th(), server=True)
        if isinstance(self._key, rsa.RSAPrivateKey):
            sig = self._key.sign(
                content,
                padding.PSS(mgf=padding.MGF1(hashes.SHA256()),
                            salt_length=hashes.SHA256.digest_size),
                hashes.SHA256())
            alg = SIG_RSA_PSS_SHA256
        elif isinstance(self._key, ec.EllipticCurvePrivateKey):
            sig = self._key.sign(content, ec.ECDSA(hashes.SHA256()))
            alg = SIG_ECDSA_P256_SHA256
        else:
            raise TlsError("unsupported server key type", 80)
        cv = _hs_msg(HT_CERTIFICATE_VERIFY,
                     struct.pack(">H", alg) + _v16(sig))
        self.transcript.update(cv)
        flight += cv

        fin = _hs_msg(HT_FINISHED,
                      self._finished_mac(self._server_hs, self._th()))
        self.transcript.update(fin)
        flight += fin
        self._send(HANDSHAKE, flight)
        self._derive_app()
        self._client_finished_due = True


# ---------------------------------------------------------------------------
# client
# ---------------------------------------------------------------------------

class Tls13Client(_Base):
    def __init__(self, server_name: str, alpn_protocols: list[str],
                 transport_params: bytes, cafile: Optional[str] = None,
                 verify: str = "required"):
        """verify='required' (default): the server chain MUST validate
        against `cafile`, or the system trust store when cafile is None —
        there is no silent fall-through to unauthenticated encryption.
        verify='none' is an explicit opt-out (test rigs, pinned
        deployments) and logs loudly. The reference gets the same default
        from msquic/platform validation."""
        super().__init__()
        self.server_name = server_name
        self._alpn = alpn_protocols
        self._tp = transport_params
        if verify not in ("required", "none"):
            raise ValueError(f"verify must be 'required' or 'none', "
                             f"got {verify!r}")
        self._verify = verify
        if verify == "required" and cafile is None:
            cafile = _system_cafile()
            if cafile is None:
                raise ValueError(
                    "no CA bundle found: pass cafile=..., or opt out "
                    "explicitly with verify='none'")
        if verify == "none":
            import logging
            logging.getLogger("emqx.quic").warning(
                "QUIC TLS verify='none': server certificate and hostname "
                "are NOT verified (connection is encrypted but "
                "unauthenticated)")
            cafile = None
        self._cafile = cafile
        self._priv = X25519PrivateKey.generate()
        self._server_cert = None

    def start(self) -> None:
        pub = self._priv.public_key().public_bytes(
            serialization.Encoding.Raw, serialization.PublicFormat.Raw)
        exts = b""
        if self.server_name:
            host = self.server_name.encode()
            exts += _ext(EXT_SNI, _v16(b"\x00" + _v16(host)))
        exts += _ext(EXT_SUPPORTED_GROUPS,
                     _v16(struct.pack(">H", GROUP_X25519)))
        exts += _ext(EXT_SIG_ALGS, _v16(struct.pack(
            ">HH", SIG_RSA_PSS_SHA256, SIG_ECDSA_P256_SHA256)))
        exts += _ext(EXT_SUPPORTED_VERSIONS, b"\x02\x03\x04")
        exts += _ext(EXT_PSK_MODES, b"\x01\x01")
        exts += _ext(EXT_KEY_SHARE, _v16(
            struct.pack(">HH", GROUP_X25519, 32) + pub))
        if self._alpn:
            exts += _ext(EXT_ALPN, _v16(b"".join(
                _v8(p.encode()) for p in self._alpn)))
        exts += _ext(EXT_QUIC_TP, self._tp)
        body = (b"\x03\x03" + os.urandom(32) + _v8(os.urandom(32))
                + _v16(struct.pack(">H", TLS_AES_128_GCM_SHA256))
                + b"\x01\x00" + _v16(exts))
        ch = _hs_msg(HT_CLIENT_HELLO, body)
        self.transcript.update(ch)
        self._send(INITIAL, ch)

    def feed_crypto(self, level: int, data: bytes) -> None:
        for htype, body, raw in self._buffers[level].feed(data):
            if htype == HT_SERVER_HELLO and level == INITIAL:
                self._on_server_hello(body, raw)
            elif level == HANDSHAKE and htype == HT_ENCRYPTED_EXTENSIONS:
                self.transcript.update(raw)
                exts = _parse_exts(body[2:])
                if EXT_QUIC_TP in exts:
                    self.peer_transport_params = exts[EXT_QUIC_TP]
                if EXT_ALPN in exts:
                    a = exts[EXT_ALPN]
                    self.alpn = a[3:3 + a[2]].decode()
            elif level == HANDSHAKE and htype == HT_CERTIFICATE:
                self._on_certificate(body, raw)
            elif level == HANDSHAKE and htype == HT_CERTIFICATE_VERIFY:
                self._on_cert_verify(body, raw)
            elif level == HANDSHAKE and htype == HT_FINISHED:
                self._on_server_finished(body, raw)
            else:
                raise TlsError(f"unexpected handshake message {htype} "
                               f"at level {level}", 10)

    def _on_server_hello(self, body: bytes, raw: bytes) -> None:
        pos = 2 + 32
        pos += 1 + body[pos]                         # session id echo
        suite = struct.unpack_from(">H", body, pos)[0]
        if suite != TLS_AES_128_GCM_SHA256:
            raise TlsError("server chose unsupported suite", 47)
        pos += 3                                     # suite + compression
        ext_len = struct.unpack_from(">H", body, pos)[0]
        exts = _parse_exts(body[pos + 2:pos + 2 + ext_len])
        ks = exts.get(EXT_KEY_SHARE, b"")
        grp, ln = struct.unpack_from(">HH", ks, 0)
        if grp != GROUP_X25519 or ln != 32:
            raise TlsError("server key share not x25519", 47)
        self.transcript.update(raw)
        shared = self._priv.exchange(
            X25519PublicKey.from_public_bytes(ks[4:36]))
        self._derive_hs(shared)

    def _on_certificate(self, body: bytes, raw: bytes) -> None:
        from cryptography import x509
        self.transcript.update(raw)
        pos = 1 + body[0]                            # certificate context
        list_end = pos + 3 + int.from_bytes(body[pos:pos + 3], "big")
        pos += 3
        chain = []
        while pos + 3 <= list_end:
            ln = int.from_bytes(body[pos:pos + 3], "big")
            chain.append(
                x509.load_der_x509_certificate(body[pos + 3:pos + 3 + ln]))
            pos += 3 + ln
            elen = struct.unpack(">H", body[pos:pos + 2])[0]
            pos += 2 + elen                          # per-entry extensions
        if not chain:
            raise TlsError("empty certificate chain", 42)
        self._server_cert = chain[0]
        if self._cafile:
            self._verify_chain(chain)

    @staticmethod
    def _is_ca(cert) -> bool:
        """RFC 5280 §4.2.1.9/.3: a cert may act as an issuer only with
        basicConstraints CA=true and (when KeyUsage is present)
        keyCertSign. Without this check any holder of an ordinary
        end-entity cert from a trusted CA could sign a fake leaf for an
        arbitrary hostname and MITM the connection."""
        from cryptography import x509
        try:
            bc = cert.extensions.get_extension_for_class(
                x509.BasicConstraints).value
            if not bc.ca:
                return False
        except x509.ExtensionNotFound:
            return False
        try:
            ku = cert.extensions.get_extension_for_class(
                x509.KeyUsage).value
            if not ku.key_cert_sign:
                return False
        except x509.ExtensionNotFound:
            pass
        return True

    def _verify_chain(self, chain: list) -> None:
        """Leaf -> (intermediates) -> trusted CA, plus validity period,
        intermediate CA constraints (basicConstraints/keyUsage) and
        hostname (SAN dNSName, wildcard leftmost label; CN fallback)."""
        import datetime

        from cryptography import x509
        with open(self._cafile, "rb") as f:
            cas = x509.load_pem_x509_certificates(f.read())
        now = datetime.datetime.now(datetime.timezone.utc)
        for cert in chain:
            if not (cert.not_valid_before_utc <= now
                    <= cert.not_valid_after_utc):
                raise TlsError("certificate outside validity period", 45)
        # walk up: each link verified by the next chain entry or a root;
        # wire-supplied intermediates must satisfy the CA constraints
        cur = chain[0]
        rest = chain[1:]
        trusted = False
        for _ in range(len(chain) + 1):
            for ca in cas:
                try:
                    cur.verify_directly_issued_by(ca)
                    trusted = True
                    break
                except Exception:  # noqa: BLE001
                    continue
            if trusted:
                break
            nxt = None
            for cand in rest:
                if not self._is_ca(cand):
                    continue
                try:
                    cur.verify_directly_issued_by(cand)
                    nxt = cand
                    break
                except Exception:  # noqa: BLE001
                    continue
            if nxt is None:
                break
            cur = nxt
            rest = [c for c in rest if c is not nxt]
        if not trusted:
            raise TlsError("server certificate not issued by trusted CA",
                           42)
        # hostname check OUTSIDE the issuer-probe try blocks — its
        # TlsError must surface, not read as an issuer mismatch
        self._check_hostname(chain[0])

    def _check_hostname(self, leaf) -> None:
        if not self.server_name:
            return
        from cryptography import x509
        from cryptography.x509.oid import NameOID
        names: list[str] = []
        try:
            san = leaf.extensions.get_extension_for_class(
                x509.SubjectAlternativeName).value
            names = list(san.get_values_for_type(x509.DNSName)) + \
                [str(ip) for ip in san.get_values_for_type(x509.IPAddress)]
        except x509.ExtensionNotFound:
            cn = leaf.subject.get_attributes_for_oid(NameOID.COMMON_NAME)
            names = [cn[0].value] if cn else []
        want = self.server_name.lower()
        for name in names:
            n = name.lower()
            if n == want:
                return
            if n.startswith("*.") and "." in want and \
                    want.split(".", 1)[1] == n[2:]:
                return
        raise TlsError(
            f"hostname {self.server_name!r} not in certificate "
            f"({names})", 42)

    def _on_cert_verify(self, body: bytes, raw: bytes) -> None:
        alg = struct.unpack_from(">H", body, 0)[0]
        sig_len = struct.unpack_from(">H", body, 2)[0]
        sig = body[4:4 + sig_len]
        content = self._cv_content(self._th(), server=True)
        pub = self._server_cert.public_key()
        try:
            if alg == SIG_RSA_PSS_SHA256:
                pub.verify(
                    sig, content,
                    padding.PSS(mgf=padding.MGF1(hashes.SHA256()),
                                salt_length=hashes.SHA256.digest_size),
                    hashes.SHA256())
            elif alg == SIG_ECDSA_P256_SHA256:
                pub.verify(sig, content, ec.ECDSA(hashes.SHA256()))
            else:
                raise TlsError(f"unsupported signature alg {alg:#x}", 47)
        except TlsError:
            raise
        except Exception as e:  # noqa: BLE001
            raise TlsError(f"CertificateVerify failed: {e}", 42)
        self.transcript.update(raw)

    def _on_server_finished(self, body: bytes, raw: bytes) -> None:
        expect = self._finished_mac(self._server_hs, self._th())
        if not hmac.compare_digest(body, expect):
            raise TlsError("bad server Finished", 51)
        self.transcript.update(raw)
        self._derive_app()
        fin = _hs_msg(HT_FINISHED,
                      self._finished_mac(self._client_hs, self._th()))
        self.transcript.update(fin)
        self._send(HANDSHAKE, fin)
        self.complete = True
