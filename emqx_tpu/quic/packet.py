"""QUIC v1 packet protection + header codec (RFC 9000 §17, RFC 9001 §5).

Long headers (Initial / Handshake) and short headers (1-RTT); AEAD is
AES-128-GCM with per-level keys derived from the TLS traffic secrets via
the "quic key"/"quic iv"/"quic hp" labels; header protection is an
AES-ECB mask over a 16-byte ciphertext sample.
"""

from __future__ import annotations

import struct
from typing import NamedTuple, Optional

from cryptography.hazmat.primitives.ciphers import Cipher, algorithms, modes
from cryptography.hazmat.primitives.ciphers.aead import AESGCM

from emqx_tpu.quic.tls13 import hkdf_expand_label, hkdf_extract

QUIC_V1 = 0x00000001
# RFC 9001 §5.2
INITIAL_SALT_V1 = bytes.fromhex(
    "38762cf7f55934b34d179ae6a4c80cadccbb7f0a")

PT_INITIAL, PT_ZERO_RTT, PT_HANDSHAKE, PT_RETRY = 0, 1, 2, 3
PT_ONE_RTT = 4


# ---------------------------------------------------------------------------
# varints (RFC 9000 §16)
# ---------------------------------------------------------------------------

def enc_varint(v: int) -> bytes:
    if v < 0x40:
        return bytes([v])
    if v < 0x4000:
        return struct.pack(">H", 0x4000 | v)
    if v < 0x40000000:
        return struct.pack(">I", 0x80000000 | v)
    return struct.pack(">Q", 0xC000000000000000 | v)


def dec_varint(data: bytes, pos: int) -> tuple[int, int]:
    first = data[pos]
    klass = first >> 6
    n = 1 << klass
    v = first & 0x3F
    for i in range(1, n):
        v = (v << 8) | data[pos + i]
    return v, pos + n


class Keys(NamedTuple):
    aead: AESGCM
    iv: bytes
    hp: bytes       # header-protection key (AES-128)


def derive_keys(secret: bytes) -> Keys:
    key = hkdf_expand_label(secret, "quic key", b"", 16)
    iv = hkdf_expand_label(secret, "quic iv", b"", 12)
    hp = hkdf_expand_label(secret, "quic hp", b"", 16)
    return Keys(aead=AESGCM(key), iv=iv, hp=hp)


def initial_secrets(dcid: bytes) -> tuple[bytes, bytes]:
    """-> (client_initial_secret, server_initial_secret) per RFC 9001."""
    initial = hkdf_extract(INITIAL_SALT_V1, dcid)
    client = hkdf_expand_label(initial, "client in", b"", 32)
    server = hkdf_expand_label(initial, "server in", b"", 32)
    return client, server


def _nonce(iv: bytes, pn: int) -> bytes:
    return (int.from_bytes(iv, "big") ^ pn).to_bytes(12, "big")


def _hp_mask(hp_key: bytes, sample: bytes) -> bytes:
    enc = Cipher(algorithms.AES(hp_key), modes.ECB()).encryptor()
    return enc.update(sample)[:5]


class Packet(NamedTuple):
    ptype: int                 # PT_*
    dcid: bytes
    scid: bytes                # long headers only
    pn: int
    payload: bytes
    token: bytes               # initial only


def encode_packet(ptype: int, version: int, dcid: bytes, scid: bytes,
                  pn: int, payload: bytes, keys: Keys,
                  token: bytes = b"", key_phase: int = 0) -> bytes:
    """Build + protect one packet. Packet numbers always encode 4 bytes
    (legal per RFC 9000 §17.1; simplifies decode on loss-free paths)."""
    pn_bytes = struct.pack(">I", pn & 0xFFFFFFFF)
    if ptype == PT_ONE_RTT:
        first = 0x40 | ((key_phase & 1) << 2) | 0x03   # pn_len-1 = 3
        header = bytes([first]) + dcid + pn_bytes
        pn_off = 1 + len(dcid)
    else:
        first = 0xC0 | (ptype << 4) | 0x03
        header = (bytes([first]) + struct.pack(">I", version)
                  + bytes([len(dcid)]) + dcid + bytes([len(scid)]) + scid)
        if ptype == PT_INITIAL:
            header += enc_varint(len(token)) + token
        length = 4 + len(payload) + 16                 # pn + body + tag
        header += enc_varint(length)
        pn_off = len(header)
        header += pn_bytes
    ct = keys.aead.encrypt(_nonce(keys.iv, pn), payload, header)
    out = bytearray(header + ct)
    sample = bytes(out[pn_off + 4:pn_off + 20])
    mask = _hp_mask(keys.hp, sample)
    out[0] ^= mask[0] & (0x0F if ptype != PT_ONE_RTT else 0x1F)
    for i in range(4):
        out[pn_off + i] ^= mask[1 + i]
    return bytes(out)


class PacketError(Exception):
    pass


# ---- Retry packets (RFC 9000 §17.2.5 + RFC 9001 §5.8 integrity tag) ----
# fixed v1 key/nonce from RFC 9001 §5.8
RETRY_KEY = bytes.fromhex("be0c690b9f66575a1d766b54e368c84e")
RETRY_NONCE = bytes.fromhex("461599d35d632bf2239825bb")


def _retry_pseudo(odcid: bytes, retry_no_tag: bytes) -> bytes:
    return bytes([len(odcid)]) + odcid + retry_no_tag


def encode_retry(version: int, dcid: bytes, scid: bytes, odcid: bytes,
                 token: bytes) -> bytes:
    """Build a Retry packet (server -> client, address validation)."""
    from cryptography.hazmat.primitives.ciphers.aead import AESGCM
    first = 0xC0 | (PT_RETRY << 4)
    pkt = (bytes([first]) + struct.pack(">I", version)
           + bytes([len(dcid)]) + dcid + bytes([len(scid)]) + scid + token)
    tag = AESGCM(RETRY_KEY).encrypt(RETRY_NONCE, b"",
                                    _retry_pseudo(odcid, pkt))
    return pkt + tag


def decode_retry(datagram: bytes, odcid: bytes):
    """Parse + integrity-check a Retry. -> (scid, token) or None when the
    tag does not verify (RFC 9001 §5.8: MUST discard on mismatch)."""
    from cryptography.hazmat.primitives.ciphers.aead import AESGCM
    if len(datagram) < 23 or (datagram[0] & 0xB0) != 0xB0:
        return None
    p = 5
    dlen = datagram[p]
    p += 1 + dlen
    if p >= len(datagram):
        return None
    slen = datagram[p]
    scid = datagram[p + 1:p + 1 + slen]
    p += 1 + slen
    if p + 16 > len(datagram):
        return None
    token = datagram[p:-16]
    tag = datagram[-16:]
    try:
        AESGCM(RETRY_KEY).decrypt(
            RETRY_NONCE, tag, _retry_pseudo(odcid, datagram[:-16]))
    except Exception:  # noqa: BLE001 — invalid tag: discard
        return None
    return scid, token


def peek_header(datagram: bytes, pos: int,
                short_dcid_len: int) -> tuple[int, bytes, bytes, bytes, int, int]:
    """Parse the unprotected parts: -> (ptype, dcid, scid, token,
    pn_offset, end). `end` = index one past this packet in the datagram."""
    first = datagram[pos]
    if first & 0x80:
        ptype = (first >> 4) & 0x03
        p = pos + 5
        dlen = datagram[p]
        dcid = datagram[p + 1:p + 1 + dlen]
        p += 1 + dlen
        slen = datagram[p]
        scid = datagram[p + 1:p + 1 + slen]
        p += 1 + slen
        token = b""
        if ptype == PT_INITIAL:
            tlen, p = dec_varint(datagram, p)
            token = datagram[p:p + tlen]
            p += tlen
        length, p = dec_varint(datagram, p)
        return ptype, dcid, scid, token, p, p + length
    dcid = datagram[pos + 1:pos + 1 + short_dcid_len]
    return PT_ONE_RTT, dcid, b"", b"", pos + 1 + short_dcid_len, \
        len(datagram)


def decode_packet(datagram: bytes, pos: int, ptype: int, pn_off: int,
                  end: int, keys: Keys, largest_pn: int) -> Packet:
    """Unprotect + decrypt one packet located by peek_header."""
    buf = bytearray(datagram[pos:end])
    rel_pn = pn_off - pos
    sample = bytes(buf[rel_pn + 4:rel_pn + 20])
    if len(sample) < 16:
        raise PacketError("short sample")
    mask = _hp_mask(keys.hp, sample)
    buf[0] ^= mask[0] & (0x0F if ptype != PT_ONE_RTT else 0x1F)
    pn_len = (buf[0] & 0x03) + 1
    for i in range(pn_len):
        buf[rel_pn + i] ^= mask[1 + i]
    truncated = int.from_bytes(buf[rel_pn:rel_pn + pn_len], "big")
    pn = _decode_pn(truncated, pn_len * 8, largest_pn)
    header = bytes(buf[:rel_pn + pn_len])
    ct = bytes(buf[rel_pn + pn_len:])
    try:
        payload = keys.aead.decrypt(_nonce(keys.iv, pn), ct, header)
    except Exception as e:  # noqa: BLE001 — InvalidTag
        raise PacketError(f"decrypt failed: {e}")
    return Packet(ptype=ptype, dcid=b"", scid=b"", pn=pn,
                  payload=payload, token=b"")


def _decode_pn(truncated: int, bits: int, largest: int) -> int:
    """RFC 9000 appendix A.3 packet-number reconstruction."""
    expected = largest + 1
    win = 1 << bits
    hwin = win // 2
    mask = win - 1
    cand = (expected & ~mask) | truncated
    if cand <= expected - hwin and cand < (1 << 62) - win:
        return cand + win
    if cand > expected + hwin and cand >= win:
        return cand - win
    return cand


# ---------------------------------------------------------------------------
# transport parameters (RFC 9000 §18)
# ---------------------------------------------------------------------------

TP_MAX_IDLE_TIMEOUT = 0x01
TP_MAX_UDP_PAYLOAD = 0x03
TP_MAX_DATA = 0x04
TP_MAX_STREAM_DATA_BIDI_LOCAL = 0x05
TP_MAX_STREAM_DATA_BIDI_REMOTE = 0x06
TP_MAX_STREAM_DATA_UNI = 0x07
TP_MAX_STREAMS_BIDI = 0x08
TP_MAX_STREAMS_UNI = 0x09
TP_INITIAL_SCID = 0x0F
TP_ORIGINAL_DCID = 0x00
TP_RETRY_SCID = 0x10


def encode_transport_params(params: dict[int, "int | bytes"]) -> bytes:
    out = b""
    for k, v in params.items():
        body = v if isinstance(v, (bytes, bytearray)) else enc_varint(v)
        out += enc_varint(k) + enc_varint(len(body)) + bytes(body)
    return out


def decode_transport_params(data: bytes) -> dict[int, bytes]:
    out: dict[int, bytes] = {}
    pos = 0
    while pos < len(data):
        k, pos = dec_varint(data, pos)
        ln, pos = dec_varint(data, pos)
        out[k] = data[pos:pos + ln]
        pos += ln
    return out
