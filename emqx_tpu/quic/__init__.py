"""QUIC v1 transport (RFC 9000/9001) with a built-in TLS 1.3 handshake.

Parity: the reference's quicer/msquic listener stack
(apps/emqx/src/emqx_quic_connection.erl, emqx_quic_stream.erl — thin
adapters over the msquic C library). No QUIC library exists in this
environment, so the transport is implemented directly over asyncio UDP +
the `cryptography` primitives: tls13.py (handshake engine), packet.py
(varints, header/packet protection), frames.py (frame codec),
connection.py (server endpoint feeding the broker Channel per stream),
client.py (test/bridge client). Scope: v1, TLS_AES_128_GCM_SHA256,
x25519, loss-free paths (immediate ACKs, no congestion controller) —
the deployment target is MQTT-over-QUIC on low-loss links; recovery is
layered in connection.py where datagram loss matters.
"""

from emqx_tpu.quic.client import QuicClientConnection   # noqa: F401
from emqx_tpu.quic.connection import QuicListener       # noqa: F401

