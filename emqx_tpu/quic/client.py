"""QUIC client endpoint: drives the conformance tests and gives the MQTT
bridge a QUIC dialing option (the reference bundles emqtt-over-quicer for
the same purposes).

`QuicClientConnection.connect()` performs the full handshake;
`open_stream()` returns (StreamReader, writer) shaped like asyncio's TCP
pair, so `emqx_tpu.client.Client` can run MQTT over it unchanged.
"""

from __future__ import annotations

import asyncio
import os
from typing import Optional

from emqx_tpu.quic import frames as F
from emqx_tpu.quic import packet as P
from emqx_tpu.quic import tls13 as T
from emqx_tpu.quic.connection import (CID_LEN, CONN_WINDOW, MAX_DATAGRAM,
                                      STREAM_WINDOW, QuicConnectionBase,
                                      _QuicStreamWriter, _RecvStream)


class QuicClientConnection(QuicConnectionBase):
    is_client = True

    def __init__(self, host: str = "127.0.0.1", port: int = 14567,
                 server_name: Optional[str] = None,
                 cafile: Optional[str] = None,
                 verify: str = "required"):
        """Server certificate verification defaults ON (against `cafile`
        or the system trust store); pass verify='none' to opt out
        explicitly (logged loudly by the TLS engine)."""
        self.host = host
        self.port = port
        if server_name is None:
            server_name = host        # RFC 6125: verify what we dialed
        scid = os.urandom(CID_LEN)
        odcid = os.urandom(CID_LEN)
        super().__init__(None, (host, port), scid=scid, dcid=odcid)
        tp = P.encode_transport_params({
            P.TP_INITIAL_SCID: scid,
            P.TP_MAX_IDLE_TIMEOUT: P.enc_varint(30000),
            P.TP_MAX_UDP_PAYLOAD: P.enc_varint(MAX_DATAGRAM),
            P.TP_MAX_DATA: P.enc_varint(CONN_WINDOW),
            P.TP_MAX_STREAM_DATA_BIDI_LOCAL: P.enc_varint(STREAM_WINDOW),
            P.TP_MAX_STREAM_DATA_BIDI_REMOTE: P.enc_varint(STREAM_WINDOW),
            P.TP_MAX_STREAMS_BIDI: P.enc_varint(16),
            P.TP_MAX_STREAMS_UNI: P.enc_varint(0),
        })
        self.tls = T.Tls13Client(server_name, ["mqtt"], tp, cafile=cafile,
                                 verify=verify)
        self._setup_initial_keys(odcid)
        self._next_stream_id = 0
        self._readers: dict[int, asyncio.StreamReader] = {}

    async def connect(self, timeout: float = 10.0) -> None:
        loop = asyncio.get_running_loop()
        conn = self

        class _Proto(asyncio.DatagramProtocol):
            def datagram_received(self, data, addr):
                try:
                    conn.datagram_received(data)
                except Exception:  # noqa: BLE001
                    conn.close(1, "client internal error")

        self.transport, _ = await loop.create_datagram_endpoint(
            _Proto, remote_addr=(self.host, self.port))
        self.addr = None          # connected UDP socket: sendto(addr=None)
        self.tls.start()
        self._pump_tls()
        self.start_pto()
        self.flush()
        await asyncio.wait_for(self.handshake_done, timeout)

    def _after_tls_progress(self) -> None:
        if self.tls.complete and not self.handshake_done.done():
            self.handshake_done.set_result(True)

    def _on_handshake_done_frame(self) -> None:
        # server confirmed; initial/handshake keys can be dropped
        self.keys_rx.pop(0, None)
        self.keys_tx.pop(0, None)

    def open_stream(self) -> tuple[asyncio.StreamReader, _QuicStreamWriter]:
        sid = self._next_stream_id
        self._next_stream_id += 4
        reader = asyncio.StreamReader()
        self._readers[sid] = reader
        self.streams_rx[sid] = _RecvStream()
        writer = _QuicStreamWriter(self, sid)
        return reader, writer

    def _on_stream_frame(self, fr: F.Stream) -> None:
        rs = self.streams_rx.get(fr.stream_id)
        reader = self._readers.get(fr.stream_id)
        if rs is None or reader is None:
            return
        if not self._enforce_stream_flow(fr, rs):
            return
        data = rs.reassembly.feed(fr.offset, fr.data)
        if fr.fin:
            rs.fin_at = fr.offset + len(fr.data)
        if data:
            rs.delivered += len(data)
            reader.feed_data(data)
            self._replenish_rx(fr.stream_id, rs, self.spaces[2])
        if rs.fin_at is not None and rs.reassembly.next >= rs.fin_at:
            reader.feed_eof()

    def _on_closed(self) -> None:
        super()._on_closed()
        for reader in self._readers.values():
            if not reader.at_eof():
                reader.feed_eof()
        if self.transport is not None:
            self.transport.close()
            self.transport = None
