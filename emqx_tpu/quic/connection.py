"""QUIC v1 endpoint machinery + the MQTT-over-QUIC server listener.

Parity: apps/emqx/src/emqx_quic_connection.erl + emqx_quic_stream.erl —
there thin callbacks over msquic; here the full endpoint: packet-number
spaces, CRYPTO reassembly, immediate-ACK policy, stream demux. Each
client-initiated bidirectional stream is bridged to the ordinary broker
`Connection` (same Channel/FSM the TCP and WS listeners feed), exactly
like the reference treats one QUIC stream as one MQTT transport.

Loss handling: ACKs are generated for every ack-eliciting packet and
un-acked CRYPTO flights are retransmitted on a coarse PTO timer —
sufficient for the low-loss links MQTT-over-QUIC targets; there is no
congestion controller (the reference delegates that to msquic).
"""

from __future__ import annotations

import asyncio
import logging
import os
import time
from typing import Optional

from emqx_tpu.quic import frames as F
from emqx_tpu.quic import packet as P
from emqx_tpu.quic import tls13 as T

log = logging.getLogger("emqx_tpu.quic")

CID_LEN = 8
MAX_DATAGRAM = 1350
STREAM_WINDOW = 1 << 20        # per-stream flow-control credit
CONN_WINDOW = 1 << 22
PTO_S = 0.3
IDLE_TIMEOUT_S = 30.0

_LVL_OF_PTYPE = {P.PT_INITIAL: T.INITIAL, P.PT_HANDSHAKE: T.HANDSHAKE,
                 P.PT_ONE_RTT: T.APPLICATION}
_PTYPE_OF_LVL = {T.INITIAL: P.PT_INITIAL, T.HANDSHAKE: P.PT_HANDSHAKE,
                 T.APPLICATION: P.PT_ONE_RTT}


class _CryptoReassembly:
    def __init__(self):
        self.next = 0
        self.frags: dict[int, bytes] = {}

    def feed(self, offset: int, data: bytes) -> bytes:
        if offset > self.next:
            self.frags[offset] = data
            return b""
        out = data[self.next - offset:] if offset < self.next else data
        self.next += len(out)
        while self.frags:
            off = min(self.frags)
            if off > self.next:
                break
            d = self.frags.pop(off)
            tail = d[self.next - off:] if off < self.next else d
            out += tail
            self.next += len(tail)
        return out


class _RecvStream:
    def __init__(self):
        self.reassembly = _CryptoReassembly()
        self.fin_at: Optional[int] = None
        self.delivered = 0
        self.credit = STREAM_WINDOW     # last advertised rx limit


class _Space:
    """One packet-number space (initial/handshake/app)."""

    def __init__(self):
        self.next_pn = 0
        self.largest_rx = -1
        self.rx_floor = -1            # every pn <= floor was received
        self.rx_pns: set[int] = set()  # received pns above the floor
        self.ack_due = False
        self.crypto_rx = _CryptoReassembly()
        # pn -> (ts, payload, ack_eliciting)
        self.unacked: dict[int, tuple[float, bytes, bool]] = {}

    def record_rx(self, pn: int) -> bool:
        """Track a received pn; False if duplicate. Compresses the
        contiguous prefix into rx_floor so state stays O(reorder window)."""
        if pn <= self.rx_floor or pn in self.rx_pns:
            return False
        self.rx_pns.add(pn)
        self.largest_rx = max(self.largest_rx, pn)
        while self.rx_floor + 1 in self.rx_pns:
            self.rx_floor += 1
            self.rx_pns.discard(self.rx_floor)
        return True


class QuicConnectionBase:
    is_client = False

    def __init__(self, transport: asyncio.DatagramTransport,
                 addr, scid: bytes, dcid: bytes):
        self.transport = transport
        self.addr = addr
        self.scid = scid
        self.dcid = dcid
        self.spaces = {lvl: _Space() for lvl in (0, 1, 2)}
        self.keys_rx: dict[int, P.Keys] = {}
        self.keys_tx: dict[int, P.Keys] = {}
        self.tls: Optional[T._Base] = None
        self.streams_rx: dict[int, _RecvStream] = {}
        self.stream_tx_offset: dict[int, int] = {}
        self._out_frames: dict[int, list[bytes]] = {0: [], 1: [], 2: []}
        self.closed = False
        self.close_reason = ""
        self.last_rx = time.monotonic()
        self.handshake_done = asyncio.get_event_loop().create_future()
        self._pto_task: Optional[asyncio.Task] = None
        # peer flow-control limits (from transport params, then MAX_*)
        self.peer_max_stream_data = 1 << 16
        self.peer_max_data = 1 << 18
        self._stream_tx_limit: dict[int, int] = {}
        self._blocked_tx: dict[int, tuple[bytes, bool]] = {}
        self._tx_total = 0

    # ---- tls plumbing ----
    def _setup_initial_keys(self, initial_dcid: bytes) -> None:
        client, server = P.initial_secrets(initial_dcid)
        mine, theirs = (client, server) if self.is_client \
            else (server, client)
        self.keys_tx[0] = P.derive_keys(mine)
        self.keys_rx[0] = P.derive_keys(theirs)

    def _pump_tls(self) -> None:
        for level, data in self.tls.pending:
            sp = self.spaces[level]
            off = getattr(sp, "crypto_tx_offset", 0)
            pos = 0
            while pos < len(data):
                chunk = data[pos:pos + 1000]
                self._out_frames[level].append(
                    F.encode_crypto(off + pos, chunk))
                pos += len(chunk)
            sp.crypto_tx_offset = off + len(data)
        self.tls.pending.clear()
        if self.tls.peer_transport_params is not None and \
                not getattr(self, "_tp_applied", False):
            self._tp_applied = True
            self._apply_peer_transport_params()
        for level, (client_s, server_s) in self.tls.secrets.items():
            if level not in self.keys_tx:
                mine, theirs = (client_s, server_s) if self.is_client \
                    else (server_s, client_s)
                self.keys_tx[level] = P.derive_keys(mine)
                self.keys_rx[level] = P.derive_keys(theirs)

    # ---- inbound ----
    def datagram_received(self, datagram: bytes) -> None:
        pos = 0
        while pos < len(datagram):
            try:
                ptype, dcid, scid, token, pn_off, end = P.peek_header(
                    datagram, pos, CID_LEN)
            except (IndexError, ValueError):
                return
            if ptype == P.PT_RETRY or ptype == P.PT_ZERO_RTT:
                pos = end if end > pos else len(datagram)
                continue
            level = _LVL_OF_PTYPE[ptype]
            keys = self.keys_rx.get(level)
            if keys is None:
                return                       # keys not ready: drop rest
            sp = self.spaces[level]
            try:
                pkt = P.decode_packet(datagram, pos, ptype, pn_off, end,
                                      keys, sp.largest_rx)
            except P.PacketError:
                pos = end if end > pos else len(datagram)
                continue
            if self.is_client and level == 0 and scid and \
                    self.dcid != scid:
                self.dcid = scid             # adopt server's chosen CID
            pos = end if end > pos else len(datagram)
            if not sp.record_rx(pkt.pn):
                continue
            self.last_rx = time.monotonic()
            try:
                self._handle_frames(level, F.parse_frames(pkt.payload))
            except (F.FrameError, T.TlsError) as e:
                self.close(0x0A if isinstance(e, F.FrameError) else
                           0x100 + getattr(e, "alert", 80), str(e))
                return
        self.flush()

    def _handle_frames(self, level: int, frames: list) -> None:
        sp = self.spaces[level]
        for fr in frames:
            if isinstance(fr, F.Ack):
                for lo, hi in fr.ranges:
                    for pn in list(sp.unacked):
                        if lo <= pn <= hi:
                            del sp.unacked[pn]
                continue
            sp.ack_due = True
            if isinstance(fr, F.Crypto):
                data = sp.crypto_rx.feed(fr.offset, fr.data)
                if data:
                    self.tls.feed_crypto(level, data)
                    self._pump_tls()
                    self._after_tls_progress()
            elif isinstance(fr, F.Stream):
                self._on_stream_frame(fr)
            elif isinstance(fr, F.Close):
                self.closed = True
                self.close_reason = fr.reason
                self._on_closed()
            elif isinstance(fr, F.HandshakeDone):
                self._on_handshake_done_frame()
            elif isinstance(fr, F.MaxStreamData):
                cur = self._stream_tx_limit.get(
                    fr.stream_id, self.peer_max_stream_data)
                self._stream_tx_limit[fr.stream_id] = max(cur, fr.value)
                self._drain_blocked()
            elif isinstance(fr, F.MaxData):
                self.peer_max_data = max(self.peer_max_data, fr.value)
                self._drain_blocked()
            elif isinstance(fr, (F.Ping, F.ResetStream)):
                pass

    # ---- outbound ----
    def send_stream(self, stream_id: int, data: bytes,
                    fin: bool = False) -> None:
        off = self.stream_tx_offset.get(stream_id, 0)
        if not data:
            if fin:
                self._out_frames[2].append(
                    F.encode_stream(stream_id, off, b"", fin=True))
            return
        # peer flow control: send only what the advertised windows allow;
        # the excess queues until MAX_STREAM_DATA/MAX_DATA credit arrives
        limit = self._stream_tx_limit.get(stream_id,
                                          self.peer_max_stream_data)
        allow = min(limit - off, self.peer_max_data - self._tx_total)
        if allow < len(data):
            take = max(0, allow)
            prev, _ = self._blocked_tx.get(stream_id, (b"", False))
            self._blocked_tx[stream_id] = (prev + data[take:], fin)
            data = data[:take]
            fin = False
            if not data:
                return
        elif stream_id in self._blocked_tx:
            # keep ordering: earlier bytes are still queued
            prev, _ = self._blocked_tx[stream_id]
            self._blocked_tx[stream_id] = (prev + data, fin)
            return
        pos = 0
        while pos < len(data):
            chunk = data[pos:pos + 1000]
            last = pos + len(chunk) >= len(data)
            self._out_frames[2].append(F.encode_stream(
                stream_id, off + pos, chunk, fin=fin and last))
            pos += len(chunk)
        self.stream_tx_offset[stream_id] = off + len(data)
        self._tx_total += len(data)

    def _drain_blocked(self) -> None:
        for sid in list(self._blocked_tx):
            data, fin = self._blocked_tx.pop(sid)
            self.send_stream(sid, data, fin=fin)

    def _apply_peer_transport_params(self) -> None:
        tp = P.decode_transport_params(self.tls.peer_transport_params
                                       or b"")
        # the peer's receive window for OUR data on client-initiated
        # bidi streams: bidi_local from the client's view, bidi_remote
        # from the server's offer
        key = P.TP_MAX_STREAM_DATA_BIDI_LOCAL if not self.is_client \
            else P.TP_MAX_STREAM_DATA_BIDI_REMOTE
        if key in tp:
            self.peer_max_stream_data = P.dec_varint(tp[key], 0)[0]
        if P.TP_MAX_DATA in tp:
            self.peer_max_data = P.dec_varint(tp[P.TP_MAX_DATA], 0)[0]

    def _replenish_rx(self, sid: int, rs: _RecvStream,
                      sp: "_Space") -> None:
        """Top up the credit we advertised once half is consumed."""
        if rs.delivered > rs.credit - STREAM_WINDOW // 2:
            rs.credit = rs.delivered + STREAM_WINDOW
            self._out_frames[2].append(
                F.encode_max_stream_data(sid, rs.credit))
            total = sum(r.delivered for r in self.streams_rx.values())
            self._out_frames[2].append(
                F.encode_max_data(total + CONN_WINDOW))

    def close(self, error_code: int = 0, reason: str = "",
              app: bool = False) -> None:
        if self.closed:
            return
        self.closed = True
        self.close_reason = reason
        level = 2 if 2 in self.keys_tx else (1 if 1 in self.keys_tx else 0)
        frame = F.encode_close(error_code, reason, app=app)
        self._send_datagram([(level, [frame])])
        self._on_closed()

    def _on_closed(self) -> None:
        if self._pto_task is not None:
            self._pto_task.cancel()
            self._pto_task = None
        if not self.handshake_done.done():
            self.handshake_done.set_exception(
                ConnectionError(f"quic closed: {self.close_reason}"))

    def flush(self) -> None:
        """Emit pending frames + due ACKs as coalesced datagrams."""
        if self.closed:
            return
        sections = []
        for level in (0, 1, 2):
            if level not in self.keys_tx:
                continue
            frames = self._out_frames[level]
            sp = self.spaces[level]
            if sp.ack_due and sp.largest_rx >= 0:
                frames = [self._ack_frame(sp)] + frames
                sp.ack_due = False
            if frames:
                sections.append((level, frames))
            self._out_frames[level] = []
        if sections:
            self._send_datagram(sections)

    @staticmethod
    def _ack_frame(sp: _Space) -> bytes:
        # ranges from the (small) out-of-order residue + the floor prefix
        ranges = []
        pns = sorted(sp.rx_pns, reverse=True)
        if pns:
            hi = lo = pns[0]
            for pn in pns[1:]:
                if pn == lo - 1:
                    lo = pn
                else:
                    ranges.append((lo, hi))
                    hi = lo = pn
            ranges.append((lo, hi))
        if sp.rx_floor >= 0:
            if ranges and ranges[-1][0] == sp.rx_floor + 1:
                ranges[-1] = (0, ranges[-1][1])
            else:
                ranges.append((0, sp.rx_floor))
        return F.encode_ack(sp.largest_rx, ranges)

    def _send_datagram(self, sections: list[tuple[int, list[bytes]]]) -> None:
        # split each level's frames into <=MTU packet payloads (frames are
        # built <=~1010 bytes so boundaries always fit), then coalesce
        # packets into datagrams under MAX_DATAGRAM
        packets: list[tuple[int, bytes, bool]] = []
        budget = MAX_DATAGRAM - 80          # header + tag headroom
        for level, frames in sections:
            cur = b""
            eliciting = False
            for fr in frames:
                if cur and len(cur) + len(fr) > budget:
                    packets.append((level, cur, eliciting))
                    cur = b""
                    eliciting = False
                cur += fr
                eliciting |= fr[0] not in (F.FT_PADDING, F.FT_ACK)
            if cur:
                packets.append((level, cur, eliciting))
        out = b""
        for level, payload, ack_eliciting in packets:
            sp = self.spaces[level]
            pn = sp.next_pn
            sp.next_pn += 1
            ptype = _PTYPE_OF_LVL[level]
            if self.is_client and ptype == P.PT_INITIAL:
                # client Initials must arrive in >=1200-byte datagrams
                need = 1200 - len(out) - (len(payload) + 60)
                if need > 0:
                    payload += b"\x00" * need
            raw = P.encode_packet(ptype, P.QUIC_V1, self.dcid, self.scid,
                                  pn, payload, self.keys_tx[level])
            if ack_eliciting:
                sp.unacked[pn] = (time.monotonic(), payload, True)
            if out and len(out) + len(raw) > MAX_DATAGRAM:
                if self.transport is not None:
                    self.transport.sendto(out, self.addr)
                out = b""
            out += raw
        if out and self.transport is not None:
            self.transport.sendto(out, self.addr)

    # ---- PTO retransmit (handshake-critical data only) ----
    def start_pto(self) -> None:
        if self._pto_task is None:
            self._pto_task = asyncio.ensure_future(self._pto_loop())

    async def _pto_loop(self) -> None:
        while not self.closed:
            await asyncio.sleep(PTO_S)
            now = time.monotonic()
            # idle timeout (RFC 9000 §10.1: the advertised
            # max_idle_timeout) — also reaps half-open handshakes, so a
            # bare-Initial flood cannot pin connection slots forever
            if now - self.last_rx > IDLE_TIMEOUT_S:
                self.close(0, "idle timeout")
                return
            for level in (0, 1, 2):
                sp = self.spaces[level]
                if level not in self.keys_tx:
                    continue
                for pn, (ts, payload, eliciting) in list(sp.unacked.items()):
                    if now - ts > PTO_S:
                        del sp.unacked[pn]
                        self._retransmit(level, payload, eliciting)

    def _retransmit(self, level: int, payload: bytes,
                    eliciting: bool) -> None:
        """Re-send a lost payload under a NEW packet number, preserving
        its ack-eliciting class (a payload that merely STARTS with an ACK
        frame is still eliciting — classifying by first byte would stop
        retransmitting a twice-lost handshake flight)."""
        sp = self.spaces[level]
        pn = sp.next_pn
        sp.next_pn += 1
        raw = P.encode_packet(_PTYPE_OF_LVL[level], P.QUIC_V1, self.dcid,
                              self.scid, pn, payload, self.keys_tx[level])
        if eliciting:
            sp.unacked[pn] = (time.monotonic(), payload, True)
        if self.transport is not None:
            self.transport.sendto(raw, self.addr)

    # ---- subclass hooks ----
    def _after_tls_progress(self) -> None: ...

    def _on_stream_frame(self, fr: F.Stream) -> None: ...

    def _on_handshake_done_frame(self) -> None: ...


# ---------------------------------------------------------------------------
# server
# ---------------------------------------------------------------------------

class _QuicStreamWriter:
    """StreamWriter-shaped adapter so broker Connection drives a QUIC
    stream exactly like a TCP socket (the emqx_quic_stream analog)."""

    class _Transport:
        def __init__(self, outer):
            self._outer = outer

        def get_write_buffer_size(self) -> int:
            return 0

        def abort(self) -> None:
            self._outer.close()

    def __init__(self, conn: "QuicServerConnection", stream_id: int):
        self._conn = conn
        self._sid = stream_id
        self._closing = False
        self.transport = self._Transport(self)

    def write(self, data: bytes) -> None:
        if not self._closing and not self._conn.closed:
            self._conn.send_stream(self._sid, data)
            self._conn.flush()

    async def drain(self) -> None:
        pass

    def is_closing(self) -> bool:
        return self._closing or self._conn.closed

    def close(self) -> None:
        if not self._closing:
            self._closing = True
            if not self._conn.closed:
                self._conn.send_stream(self._sid, b"", fin=True)
                self._conn.flush()

    async def wait_closed(self) -> None:
        pass

    def get_extra_info(self, name, default=None):
        if name == "peername":
            return self._conn.addr
        if name == "sockname":
            return self._conn.transport.get_extra_info("sockname", default)
        return default


class QuicServerConnection(QuicConnectionBase):
    is_client = False

    def __init__(self, listener: "QuicListener", transport, addr,
                 odcid: bytes, client_scid: bytes):
        super().__init__(transport, addr, scid=os.urandom(CID_LEN),
                         dcid=client_scid)
        self.listener = listener
        self.odcid = odcid
        tp = P.encode_transport_params({
            P.TP_ORIGINAL_DCID: odcid,
            P.TP_INITIAL_SCID: self.scid,
            P.TP_MAX_IDLE_TIMEOUT: P.enc_varint(30000),
            P.TP_MAX_UDP_PAYLOAD: P.enc_varint(MAX_DATAGRAM),
            P.TP_MAX_DATA: P.enc_varint(CONN_WINDOW),
            P.TP_MAX_STREAM_DATA_BIDI_LOCAL: P.enc_varint(STREAM_WINDOW),
            P.TP_MAX_STREAM_DATA_BIDI_REMOTE: P.enc_varint(STREAM_WINDOW),
            P.TP_MAX_STREAMS_BIDI: P.enc_varint(16),
            P.TP_MAX_STREAMS_UNI: P.enc_varint(0),
        })
        self.tls = T.Tls13Server(listener.certfile, listener.keyfile,
                                 ["mqtt"], tp)
        self._setup_initial_keys(odcid)
        self._done_sent = False
        self._readers: dict[int, asyncio.StreamReader] = {}
        self._conn_tasks: dict[int, asyncio.Task] = {}

    def _after_tls_progress(self) -> None:
        if self.tls.complete and not self._done_sent:
            self._done_sent = True
            self._out_frames[2].append(F.encode_handshake_done())
            if not self.handshake_done.done():
                self.handshake_done.set_result(True)

    def _on_stream_frame(self, fr: F.Stream) -> None:
        sid = fr.stream_id
        if sid % 4 != 0:       # only client-initiated bidi carries MQTT
            return
        rs = self.streams_rx.get(sid)
        if rs is None:
            rs = self.streams_rx[sid] = _RecvStream()
            reader = asyncio.StreamReader()
            self._readers[sid] = reader
            writer = _QuicStreamWriter(self, sid)
            self._conn_tasks[sid] = asyncio.ensure_future(
                self.listener._run_mqtt_connection(reader, writer))
        data = rs.reassembly.feed(fr.offset, fr.data)
        if fr.fin:
            rs.fin_at = fr.offset + len(fr.data)
        reader = self._readers[sid]
        if data:
            rs.delivered += len(data)
            reader.feed_data(data)
            self._replenish_rx(sid, rs, self.spaces[2])
        if rs.fin_at is not None and rs.reassembly.next >= rs.fin_at:
            reader.feed_eof()

    def _on_closed(self) -> None:
        super()._on_closed()
        for reader in self._readers.values():
            if not reader.at_eof():
                reader.feed_eof()
        self.listener._forget(self)


class QuicListener:
    """UDP endpoint accepting MQTT-over-QUIC connections
    (emqx_listeners.erl quic listener analog)."""

    protocol = "mqtt:quic"

    def __init__(self, node, *, bind: str = "0.0.0.0", port: int = 14567,
                 certfile: str, keyfile: str,
                 zone: Optional[str] = None,
                 max_connections: int = 1024000):
        self.node = node
        self.bind = bind
        self.port = port
        self.certfile = certfile
        self.keyfile = keyfile
        self.zone = zone
        self.max_connections = max_connections
        self.current_conns = 0
        self._transport: Optional[asyncio.DatagramTransport] = None
        self._conns: dict[bytes, QuicServerConnection] = {}
        self._mqtt_tasks: set[asyncio.Task] = set()

    async def start(self) -> None:
        loop = asyncio.get_running_loop()
        lst = self

        class _Proto(asyncio.DatagramProtocol):
            def connection_made(self, transport):
                lst._transport = transport

            def datagram_received(self, data, addr):
                lst._on_datagram(data, addr)

        self._transport, _ = await loop.create_datagram_endpoint(
            _Proto, local_addr=(self.bind, self.port))
        if self.port == 0:
            self.port = self._transport.get_extra_info("sockname")[1]
        log.info("quic listener started on %s:%d", self.bind, self.port)

    async def stop(self) -> None:
        for conn in list(self._conns.values()):
            conn.close(0, "server shutdown")
        for t in list(self._mqtt_tasks):
            t.cancel()
        if self._mqtt_tasks:
            await asyncio.gather(*self._mqtt_tasks, return_exceptions=True)
        if self._transport:
            self._transport.close()

    def _on_datagram(self, data: bytes, addr) -> None:
        if len(data) < CID_LEN + 1:
            return
        try:
            ptype, dcid, scid, _tok, _pn, _end = P.peek_header(
                data, 0, CID_LEN)
        except (IndexError, ValueError):
            return
        conn = self._conns.get(dcid)
        if conn is None and ptype == P.PT_INITIAL:
            if self.current_conns >= self.max_connections:
                return
            conn = QuicServerConnection(self, self._transport, addr,
                                        odcid=dcid, client_scid=scid)
            self.current_conns += 1
            # route future packets by both the original DCID (more client
            # Initials) and the server-chosen SCID (handshake/1-RTT)
            self._conns[dcid] = conn
            self._conns[conn.scid] = conn
            conn.start_pto()
        if conn is None:
            return
        conn.addr = addr
        try:
            conn.datagram_received(data)
        except Exception:  # noqa: BLE001
            log.exception("quic connection crashed")
            conn.close(1, "internal error")

    def _forget(self, conn: QuicServerConnection) -> None:
        removed = False
        for key in (conn.odcid, conn.scid):
            if self._conns.pop(key, None) is not None:
                removed = True
        if removed:
            self.current_conns -= 1

    async def _run_mqtt_connection(self, reader, writer) -> None:
        from emqx_tpu.broker.connection import Connection
        conn = Connection(self.node, reader, writer, self.zone)
        task = asyncio.current_task()
        self._mqtt_tasks.add(task)
        try:
            await conn.run()
        finally:
            self._mqtt_tasks.discard(task)
