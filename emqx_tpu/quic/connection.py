"""QUIC v1 endpoint machinery + the MQTT-over-QUIC server listener.

Parity: apps/emqx/src/emqx_quic_connection.erl + emqx_quic_stream.erl —
there thin callbacks over msquic; here the full endpoint: packet-number
spaces, CRYPTO reassembly, immediate-ACK policy, stream demux. Each
client-initiated bidirectional stream is bridged to the ordinary broker
`Connection` (same Channel/FSM the TCP and WS listeners feed), exactly
like the reference treats one QUIC stream as one MQTT transport.

Loss handling & hardening (round-3: the reference gets these from msquic):
- ACKs for every ack-eliciting packet; lost packets detected both by the
  packet-threshold rule (acked pn >= pn + 3, RFC 9002 §6.1) and a coarse
  PTO timer; retransmission under new packet numbers.
- NewReno congestion controller (RFC 9002 §7): slow start / congestion
  avoidance / halving on loss, gating application stream data.
- Anti-amplification (RFC 9000 §8): a server sends at most 3x the bytes
  received from an unvalidated address; receipt of a handshake-level
  packet (or a valid Retry token) validates the path.
- Address validation via stateless Retry tokens (RFC 9000 §8.1.2).
- Peer address updates only after an AEAD-authenticated packet from the
  new address — a spoofed datagram with an observed CID cannot redirect
  the connection (RFC 9000 §9).
- Inbound flow-control enforcement: stream data beyond the advertised
  credit, crypto floods, and excess stream ids close the connection
  instead of buffering without bound.
"""

from __future__ import annotations

import asyncio
import logging
import os
import time
from typing import Optional

from emqx_tpu.quic import frames as F
from emqx_tpu.quic import packet as P
from emqx_tpu.quic import tls13 as T

log = logging.getLogger("emqx_tpu.quic")

CID_LEN = 8
MAX_DATAGRAM = 1350
STREAM_WINDOW = 1 << 20        # per-stream flow-control credit
CONN_WINDOW = 1 << 22
MAX_STREAMS_BIDI = 16          # advertised + enforced inbound
CRYPTO_BUFFER_MAX = 1 << 17    # handshake reassembly bound
PTO_S = 0.3
IDLE_TIMEOUT_S = 30.0
# RFC 9002 §7.2 congestion defaults
INITIAL_CWND = 10 * 1200
MIN_CWND = 2 * 1200
LOSS_PN_THRESHOLD = 3          # RFC 9002 §6.1.1 packet threshold
AMPLIFICATION_LIMIT = 3        # RFC 9000 §8.1 pre-validation send factor

_LVL_OF_PTYPE = {P.PT_INITIAL: T.INITIAL, P.PT_HANDSHAKE: T.HANDSHAKE,
                 P.PT_ONE_RTT: T.APPLICATION}
_PTYPE_OF_LVL = {T.INITIAL: P.PT_INITIAL, T.HANDSHAKE: P.PT_HANDSHAKE,
                 T.APPLICATION: P.PT_ONE_RTT}


class _CryptoReassembly:
    def __init__(self, max_buffer: Optional[int] = None):
        self.next = 0
        self.frags: dict[int, bytes] = {}
        self.max_buffer = max_buffer

    def feed(self, offset: int, data: bytes) -> bytes:
        if self.max_buffer is not None and \
                offset + len(data) > self.next + self.max_buffer:
            # advertised-credit violation / reassembly flood (ADVICE
            # round-2 low): close instead of buffering without bound
            raise F.FrameError("reassembly buffer exceeded")
        if offset > self.next:
            self.frags[offset] = data
            return b""
        out = data[self.next - offset:] if offset < self.next else data
        self.next += len(out)
        while self.frags:
            off = min(self.frags)
            if off > self.next:
                break
            d = self.frags.pop(off)
            tail = d[self.next - off:] if off < self.next else d
            out += tail
            self.next += len(tail)
        return out


class _RecvStream:
    def __init__(self):
        # per-stream credit enforcement bounds the reassembly window too
        self.reassembly = _CryptoReassembly(max_buffer=2 * STREAM_WINDOW)
        self.fin_at: Optional[int] = None
        self.delivered = 0
        self.credit = STREAM_WINDOW     # last advertised rx limit
        self.highest = 0                # highest offset seen (flow acct)


class _Space:
    """One packet-number space (initial/handshake/app)."""

    def __init__(self):
        self.next_pn = 0
        self.largest_rx = -1
        self.rx_floor = -1            # every pn <= floor was received
        self.rx_pns: set[int] = set()  # received pns above the floor
        self.ack_due = False
        self.crypto_rx = _CryptoReassembly(max_buffer=CRYPTO_BUFFER_MAX)
        # pn -> (ts, payload, ack_eliciting, size)
        self.unacked: dict[int, tuple[float, bytes, bool, int]] = {}

    def record_rx(self, pn: int) -> bool:
        """Track a received pn; False if duplicate. Compresses the
        contiguous prefix into rx_floor so state stays O(reorder window)."""
        if pn <= self.rx_floor or pn in self.rx_pns:
            return False
        self.rx_pns.add(pn)
        self.largest_rx = max(self.largest_rx, pn)
        while self.rx_floor + 1 in self.rx_pns:
            self.rx_floor += 1
            self.rx_pns.discard(self.rx_floor)
        return True


class QuicConnectionBase:
    is_client = False

    def __init__(self, transport: asyncio.DatagramTransport,
                 addr, scid: bytes, dcid: bytes):
        self.transport = transport
        self.addr = addr
        self.scid = scid
        self.dcid = dcid
        self.spaces = {lvl: _Space() for lvl in (0, 1, 2)}
        self.keys_rx: dict[int, P.Keys] = {}
        self.keys_tx: dict[int, P.Keys] = {}
        self.tls: Optional[T._Base] = None
        self.streams_rx: dict[int, _RecvStream] = {}
        self.stream_tx_offset: dict[int, int] = {}
        self._out_frames: dict[int, list[bytes]] = {0: [], 1: [], 2: []}
        self.closed = False
        self.close_reason = ""
        self.last_rx = time.monotonic()
        self.handshake_done = asyncio.get_event_loop().create_future()
        self._pto_task: Optional[asyncio.Task] = None
        # peer flow-control limits (from transport params, then MAX_*)
        self.peer_max_stream_data = 1 << 16
        self.peer_max_data = 1 << 18
        self._stream_tx_limit: dict[int, int] = {}
        self._blocked_tx: dict[int, tuple[bytes, bool]] = {}
        self._tx_total = 0
        # anti-amplification (RFC 9000 §8): servers limit pre-validation
        # sends to AMPLIFICATION_LIMIT x bytes received from the address
        self.path_validated = self.is_client
        self._rx_budget_bytes = 0
        self._tx_budget_bytes = 0
        # NewReno congestion state (RFC 9002 §7), gating app stream data
        self.cwnd = INITIAL_CWND
        self.ssthresh = float("inf")
        self.bytes_in_flight = 0
        self._recovery_until = -1.0   # losses in this window: one event
        # address-validation token (client: from a Retry; echoed in
        # every subsequent Initial)
        self.initial_token = b""
        self._saw_retry = False
        # inbound flow accounting (advertised credits, enforced)
        self._conn_rx_credit = CONN_WINDOW
        self._rx_flow_total = 0

    # ---- tls plumbing ----
    def _setup_initial_keys(self, initial_dcid: bytes) -> None:
        client, server = P.initial_secrets(initial_dcid)
        mine, theirs = (client, server) if self.is_client \
            else (server, client)
        self.keys_tx[0] = P.derive_keys(mine)
        self.keys_rx[0] = P.derive_keys(theirs)

    def _pump_tls(self) -> None:
        for level, data in self.tls.pending:
            sp = self.spaces[level]
            off = getattr(sp, "crypto_tx_offset", 0)
            pos = 0
            while pos < len(data):
                chunk = data[pos:pos + 1000]
                self._out_frames[level].append(
                    F.encode_crypto(off + pos, chunk))
                pos += len(chunk)
            sp.crypto_tx_offset = off + len(data)
        self.tls.pending.clear()
        if self.tls.peer_transport_params is not None and \
                not getattr(self, "_tp_applied", False):
            self._tp_applied = True
            self._apply_peer_transport_params()
        for level, (client_s, server_s) in self.tls.secrets.items():
            if level not in self.keys_tx:
                mine, theirs = (client_s, server_s) if self.is_client \
                    else (server_s, client_s)
                self.keys_tx[level] = P.derive_keys(mine)
                self.keys_rx[level] = P.derive_keys(theirs)

    # ---- inbound ----
    def datagram_received(self, datagram: bytes, addr=None) -> None:
        if not self.path_validated:
            self._rx_budget_bytes += len(datagram)
        pos = 0
        while pos < len(datagram):
            if (datagram[pos] & 0xF0) == 0xF0:
                # long-header type 3 (Retry): handle before peek_header —
                # Retry has no length field, so generic parsing misreads
                if self.is_client:
                    self._on_retry(datagram[pos:])
                return
            try:
                ptype, dcid, scid, token, pn_off, end = P.peek_header(
                    datagram, pos, CID_LEN)
            except (IndexError, ValueError):
                return
            if ptype == P.PT_RETRY:
                if self.is_client:
                    # re-parse from the raw bytes: Retry has no
                    # length/pn fields, so peek_header's offsets past
                    # the CIDs are meaningless for it
                    self._on_retry(datagram[pos:])
                return                       # Retry is never coalesced
            if ptype == P.PT_ZERO_RTT:
                pos = end if end > pos else len(datagram)
                continue
            level = _LVL_OF_PTYPE[ptype]
            keys = self.keys_rx.get(level)
            if keys is None:
                return                       # keys not ready: drop rest
            sp = self.spaces[level]
            try:
                pkt = P.decode_packet(datagram, pos, ptype, pn_off, end,
                                      keys, sp.largest_rx)
            except P.PacketError:
                pos = end if end > pos else len(datagram)
                continue
            # the packet authenticated (AEAD) — only NOW may it update
            # the peer address (RFC 9000 §9: a spoofed datagram carrying
            # an observed CID must not redirect the connection)
            if addr is not None and addr != self.addr:
                self.addr = addr
            if level >= 1 and not self.path_validated:
                # a handshake-level packet proves the peer holds the
                # handshake keys, which required receiving our Initial
                # flight at its claimed address (RFC 9001 §4.3 handshake
                # confirmation => address validated)
                self.path_validated = True
            if self.is_client and level == 0 and scid and \
                    self.dcid != scid:
                self.dcid = scid             # adopt server's chosen CID
            pos = end if end > pos else len(datagram)
            if not sp.record_rx(pkt.pn):
                continue
            self.last_rx = time.monotonic()
            try:
                self._handle_frames(level, F.parse_frames(pkt.payload))
            except (F.FrameError, T.TlsError) as e:
                self.close(0x0A if isinstance(e, F.FrameError) else
                           0x100 + getattr(e, "alert", 80), str(e))
                return
        self.flush()

    def _on_retry(self, datagram: bytes) -> None:
        """Client side of address validation (RFC 9000 §8.1.2): adopt the
        server's new CID + token, re-derive Initial keys, and re-send the
        Initial flight. At most one Retry per connection is honored."""
        # RFC 9000 §17.2.5.2: discard Retry once ANY server packet was
        # processed — handshake keys install on the ServerHello in the
        # server's Initial, so gate on level 1, not 1-RTT (the Retry tag
        # key is public; a mid-handshake injected Retry must not reset us)
        if self._saw_retry or 1 in self.keys_rx:
            return
        parsed = P.decode_retry(datagram, self.dcid)
        if parsed is None:
            return                           # bad integrity tag: discard
        new_scid, token = parsed
        if not token:
            return
        self._saw_retry = True
        self.initial_token = token
        self.dcid = new_scid
        self._setup_initial_keys(new_scid)
        # re-send the Initial CRYPTO flight under the new keys/token;
        # packet numbers continue (RFC 9000 §17.2.5.3)
        sp = self.spaces[0]
        flights = [(payload, eliciting)
                   for _ts, payload, eliciting, _sz in sp.unacked.values()]
        sp.unacked.clear()
        for payload, eliciting in flights:
            self._retransmit(0, payload, eliciting)

    def _handle_frames(self, level: int, frames: list) -> None:
        sp = self.spaces[level]
        for fr in frames:
            if isinstance(fr, F.Ack):
                self._on_ack(level, sp, fr)
                continue
            sp.ack_due = True
            if isinstance(fr, F.Crypto):
                data = sp.crypto_rx.feed(fr.offset, fr.data)
                if data:
                    self.tls.feed_crypto(level, data)
                    self._pump_tls()
                    self._after_tls_progress()
            elif isinstance(fr, F.Stream):
                self._on_stream_frame(fr)
            elif isinstance(fr, F.Close):
                self.closed = True
                self.close_reason = fr.reason
                self._on_closed()
            elif isinstance(fr, F.HandshakeDone):
                self._on_handshake_done_frame()
            elif isinstance(fr, F.MaxStreamData):
                cur = self._stream_tx_limit.get(
                    fr.stream_id, self.peer_max_stream_data)
                self._stream_tx_limit[fr.stream_id] = max(cur, fr.value)
                self._drain_blocked()
            elif isinstance(fr, F.MaxData):
                self.peer_max_data = max(self.peer_max_data, fr.value)
                self._drain_blocked()
            elif isinstance(fr, (F.Ping, F.ResetStream)):
                pass

    def _on_ack(self, level: int, sp: _Space, fr: "F.Ack") -> None:
        """ACK processing: free in-flight bytes, grow cwnd (NewReno slow
        start / congestion avoidance), and declare packets below the
        packet-reordering threshold lost (RFC 9002 §6.1.1) — fast
        retransmit without waiting for the PTO."""
        acked_bytes = 0
        for lo, hi in fr.ranges:
            for pn in list(sp.unacked):
                if lo <= pn <= hi:
                    _ts, _payload, _el, size = sp.unacked.pop(pn)
                    if level == 2:
                        self.bytes_in_flight -= size
                        acked_bytes += size
        if acked_bytes and level == 2:
            if self.cwnd < self.ssthresh:
                self.cwnd += acked_bytes                 # slow start
            else:
                self.cwnd += 1200 * acked_bytes // max(self.cwnd, 1)
            self._drain_blocked()
        # packet-threshold loss: anything LOSS_PN_THRESHOLD below the
        # largest acked that is still unacked is lost
        lost_cut = fr.largest - LOSS_PN_THRESHOLD
        lost = [pn for pn in sp.unacked if pn <= lost_cut]
        for pn in sorted(lost):
            ts, payload, eliciting, size = sp.unacked.pop(pn)
            if level == 2:
                self.bytes_in_flight -= size
                self._congestion_event(ts)
            self._retransmit(level, payload, eliciting)

    def _congestion_event(self, sent_ts: float) -> None:
        """NewReno halving, once per recovery window (RFC 9002 §7.3.1)."""
        if sent_ts <= self._recovery_until:
            return
        self._recovery_until = time.monotonic()
        self.ssthresh = max(self.cwnd // 2, MIN_CWND)
        self.cwnd = self.ssthresh

    # ---- outbound ----
    def send_stream(self, stream_id: int, data: bytes,
                    fin: bool = False) -> None:
        off = self.stream_tx_offset.get(stream_id, 0)
        if not data:
            if fin:
                self._out_frames[2].append(
                    F.encode_stream(stream_id, off, b"", fin=True))
            return
        # peer flow control + congestion: send only what the advertised
        # windows AND the congestion window allow; the excess queues until
        # MAX_STREAM_DATA/MAX_DATA credit or ACKs free the pipe
        limit = self._stream_tx_limit.get(stream_id,
                                          self.peer_max_stream_data)
        allow = min(limit - off, self.peer_max_data - self._tx_total,
                    self.cwnd - self.bytes_in_flight)
        if allow < len(data):
            take = max(0, allow)
            prev, _ = self._blocked_tx.get(stream_id, (b"", False))
            self._blocked_tx[stream_id] = (prev + data[take:], fin)
            data = data[:take]
            fin = False
            if not data:
                return
        elif stream_id in self._blocked_tx:
            # keep ordering: earlier bytes are still queued
            prev, _ = self._blocked_tx[stream_id]
            self._blocked_tx[stream_id] = (prev + data, fin)
            return
        pos = 0
        while pos < len(data):
            chunk = data[pos:pos + 1000]
            last = pos + len(chunk) >= len(data)
            self._out_frames[2].append(F.encode_stream(
                stream_id, off + pos, chunk, fin=fin and last))
            pos += len(chunk)
        self.stream_tx_offset[stream_id] = off + len(data)
        self._tx_total += len(data)

    def _drain_blocked(self) -> None:
        for sid in list(self._blocked_tx):
            data, fin = self._blocked_tx.pop(sid)
            self.send_stream(sid, data, fin=fin)

    def _apply_peer_transport_params(self) -> None:
        tp = P.decode_transport_params(self.tls.peer_transport_params
                                       or b"")
        # the peer's receive window for OUR data on client-initiated
        # bidi streams: bidi_local from the client's view, bidi_remote
        # from the server's offer
        key = P.TP_MAX_STREAM_DATA_BIDI_LOCAL if not self.is_client \
            else P.TP_MAX_STREAM_DATA_BIDI_REMOTE
        if key in tp:
            self.peer_max_stream_data = P.dec_varint(tp[key], 0)[0]
        if P.TP_MAX_DATA in tp:
            self.peer_max_data = P.dec_varint(tp[P.TP_MAX_DATA], 0)[0]

    def _replenish_rx(self, sid: int, rs: _RecvStream,
                      sp: "_Space") -> None:
        """Top up the credit we advertised once half is consumed."""
        if rs.delivered > rs.credit - STREAM_WINDOW // 2:
            rs.credit = rs.delivered + STREAM_WINDOW
            self._out_frames[2].append(
                F.encode_max_stream_data(sid, rs.credit))
            total = sum(r.delivered for r in self.streams_rx.values())
            self._conn_rx_credit = total + CONN_WINDOW
            self._out_frames[2].append(
                F.encode_max_data(self._conn_rx_credit))

    def _enforce_stream_flow(self, fr: "F.Stream",
                             rs: _RecvStream) -> bool:
        """Inbound flow-control enforcement (ADVICE round-2): data beyond
        the advertised per-stream or connection credit closes the
        connection with FLOW_CONTROL_ERROR instead of buffering without
        bound. Returns False when the connection was closed."""
        end = fr.offset + len(fr.data)
        if end > rs.credit:
            self.close(0x03, "stream flow-control credit exceeded")
            return False
        if end > rs.highest:
            self._rx_flow_total += end - rs.highest
            rs.highest = end
            if self._rx_flow_total > self._conn_rx_credit:
                self.close(0x03, "connection flow-control credit exceeded")
                return False
        return True

    def close(self, error_code: int = 0, reason: str = "",
              app: bool = False) -> None:
        if self.closed:
            return
        self.closed = True
        self.close_reason = reason
        level = 2 if 2 in self.keys_tx else (1 if 1 in self.keys_tx else 0)
        frame = F.encode_close(error_code, reason, app=app)
        self._send_datagram([(level, [frame])])
        self._on_closed()

    def _on_closed(self) -> None:
        if self._pto_task is not None:
            self._pto_task.cancel()
            self._pto_task = None
        if not self.handshake_done.done():
            self.handshake_done.set_exception(
                ConnectionError(f"quic closed: {self.close_reason}"))

    def flush(self) -> None:
        """Emit pending frames + due ACKs as coalesced datagrams."""
        if self.closed:
            return
        sections = []
        for level in (0, 1, 2):
            if level not in self.keys_tx:
                continue
            frames = self._out_frames[level]
            sp = self.spaces[level]
            if sp.ack_due and sp.largest_rx >= 0:
                frames = [self._ack_frame(sp)] + frames
                sp.ack_due = False
            if frames:
                sections.append((level, frames))
            self._out_frames[level] = []
        if sections:
            self._send_datagram(sections)

    @staticmethod
    def _ack_frame(sp: _Space) -> bytes:
        # ranges from the (small) out-of-order residue + the floor prefix
        ranges = []
        pns = sorted(sp.rx_pns, reverse=True)
        if pns:
            hi = lo = pns[0]
            for pn in pns[1:]:
                if pn == lo - 1:
                    lo = pn
                else:
                    ranges.append((lo, hi))
                    hi = lo = pn
            ranges.append((lo, hi))
        if sp.rx_floor >= 0:
            if ranges and ranges[-1][0] == sp.rx_floor + 1:
                ranges[-1] = (0, ranges[-1][1])
            else:
                ranges.append((0, sp.rx_floor))
        return F.encode_ack(sp.largest_rx, ranges)

    def _send_datagram(self, sections: list[tuple[int, list[bytes]]]) -> None:
        # split each level's frames into <=MTU packet payloads (frames are
        # built <=~1010 bytes so boundaries always fit), then coalesce
        # packets into datagrams under MAX_DATAGRAM
        packets: list[tuple[int, bytes, bool]] = []
        budget = MAX_DATAGRAM - 80          # header + tag headroom
        for level, frames in sections:
            cur = b""
            eliciting = False
            for fr in frames:
                if cur and len(cur) + len(fr) > budget:
                    packets.append((level, cur, eliciting))
                    cur = b""
                    eliciting = False
                cur += fr
                eliciting |= fr[0] not in (F.FT_PADDING, F.FT_ACK)
            if cur:
                packets.append((level, cur, eliciting))
        out = b""
        for level, payload, ack_eliciting in packets:
            sp = self.spaces[level]
            pn = sp.next_pn
            sp.next_pn += 1
            ptype = _PTYPE_OF_LVL[level]
            if self.is_client and ptype == P.PT_INITIAL:
                # client Initials must arrive in >=1200-byte datagrams
                need = 1200 - len(out) - (len(payload) + 60)
                if need > 0:
                    payload += b"\x00" * need
            raw = P.encode_packet(
                ptype, P.QUIC_V1, self.dcid, self.scid, pn, payload,
                self.keys_tx[level],
                token=self.initial_token if ptype == P.PT_INITIAL else b"")
            if ack_eliciting:
                sp.unacked[pn] = (time.monotonic(), payload, True,
                                  len(raw))
                if level == 2:
                    self.bytes_in_flight += len(raw)
            if out and len(out) + len(raw) > MAX_DATAGRAM:
                self._sendto(out)
                out = b""
            out += raw
        if out:
            self._sendto(out)

    def _sendto(self, datagram: bytes) -> None:
        """Socket send behind the anti-amplification gate: before address
        validation a server sends at most AMPLIFICATION_LIMIT x the bytes
        received (RFC 9000 §8.1) — a spoofed-source Initial cannot turn
        the cert flight into a reflection amplifier. Blocked packets stay
        in `unacked`, so the PTO re-sends them once credit arrives."""
        if self.transport is None:
            return
        if not self.path_validated:
            if (self._tx_budget_bytes + len(datagram)
                    > AMPLIFICATION_LIMIT * self._rx_budget_bytes):
                return
            self._tx_budget_bytes += len(datagram)
        self.transport.sendto(datagram, self.addr)

    # ---- PTO retransmit (handshake-critical data only) ----
    def start_pto(self) -> None:
        if self._pto_task is None:
            self._pto_task = asyncio.ensure_future(self._pto_loop())

    async def _pto_loop(self) -> None:
        while not self.closed:
            await asyncio.sleep(PTO_S)
            now = time.monotonic()
            # idle timeout (RFC 9000 §10.1: the advertised
            # max_idle_timeout) — also reaps half-open handshakes, so a
            # bare-Initial flood cannot pin connection slots forever
            if now - self.last_rx > IDLE_TIMEOUT_S:
                self.close(0, "idle timeout")
                return
            for level in (0, 1, 2):
                sp = self.spaces[level]
                if level not in self.keys_tx:
                    continue
                for pn, (ts, payload, eliciting, size) in \
                        list(sp.unacked.items()):
                    if now - ts > PTO_S:
                        del sp.unacked[pn]
                        if level == 2:
                            self.bytes_in_flight -= size
                            self._congestion_event(ts)
                        self._retransmit(level, payload, eliciting)

    def _retransmit(self, level: int, payload: bytes,
                    eliciting: bool) -> None:
        """Re-send a lost payload under a NEW packet number, preserving
        its ack-eliciting class (a payload that merely STARTS with an ACK
        frame is still eliciting — classifying by first byte would stop
        retransmitting a twice-lost handshake flight)."""
        sp = self.spaces[level]
        pn = sp.next_pn
        sp.next_pn += 1
        ptype = _PTYPE_OF_LVL[level]
        raw = P.encode_packet(
            ptype, P.QUIC_V1, self.dcid, self.scid, pn, payload,
            self.keys_tx[level],
            token=self.initial_token if ptype == P.PT_INITIAL else b"")
        if eliciting:
            sp.unacked[pn] = (time.monotonic(), payload, True, len(raw))
            if level == 2:
                self.bytes_in_flight += len(raw)
        self._sendto(raw)

    # ---- subclass hooks ----
    def _after_tls_progress(self) -> None: ...

    def _on_stream_frame(self, fr: F.Stream) -> None: ...

    def _on_handshake_done_frame(self) -> None: ...


# ---------------------------------------------------------------------------
# server
# ---------------------------------------------------------------------------

class _QuicStreamWriter:
    """StreamWriter-shaped adapter so broker Connection drives a QUIC
    stream exactly like a TCP socket (the emqx_quic_stream analog)."""

    class _Transport:
        def __init__(self, outer):
            self._outer = outer

        def get_write_buffer_size(self) -> int:
            return 0

        def abort(self) -> None:
            self._outer.close()

    def __init__(self, conn: "QuicServerConnection", stream_id: int):
        self._conn = conn
        self._sid = stream_id
        self._closing = False
        self.transport = self._Transport(self)

    def write(self, data: bytes) -> None:
        if not self._closing and not self._conn.closed:
            self._conn.send_stream(self._sid, data)
            self._conn.flush()

    async def drain(self) -> None:
        pass

    def is_closing(self) -> bool:
        return self._closing or self._conn.closed

    def close(self) -> None:
        if not self._closing:
            self._closing = True
            if not self._conn.closed:
                self._conn.send_stream(self._sid, b"", fin=True)
                self._conn.flush()

    async def wait_closed(self) -> None:
        pass

    def get_extra_info(self, name, default=None):
        if name == "peername":
            return self._conn.addr
        if name == "sockname":
            return self._conn.transport.get_extra_info("sockname", default)
        return default


class QuicServerConnection(QuicConnectionBase):
    is_client = False

    def __init__(self, listener: "QuicListener", transport, addr,
                 odcid: bytes, client_scid: bytes,
                 initial_dcid: Optional[bytes] = None,
                 retry_scid: Optional[bytes] = None):
        """odcid: the client's ORIGINAL destination CID (echoed in
        transport params). initial_dcid: the DCID the Initial keys derive
        from — after a Retry that is the retry SCID the client adopted,
        not the original. retry_scid: set when this connection resumed
        from a Retry token (echoed as TP_RETRY_SCID, RFC 9000 §18.2)."""
        super().__init__(transport, addr, scid=os.urandom(CID_LEN),
                         dcid=client_scid)
        self.listener = listener
        self.odcid = odcid
        params = {
            P.TP_ORIGINAL_DCID: odcid,
            P.TP_INITIAL_SCID: self.scid,
            P.TP_MAX_IDLE_TIMEOUT: P.enc_varint(30000),
            P.TP_MAX_UDP_PAYLOAD: P.enc_varint(MAX_DATAGRAM),
            P.TP_MAX_DATA: P.enc_varint(CONN_WINDOW),
            P.TP_MAX_STREAM_DATA_BIDI_LOCAL: P.enc_varint(STREAM_WINDOW),
            P.TP_MAX_STREAM_DATA_BIDI_REMOTE: P.enc_varint(STREAM_WINDOW),
            P.TP_MAX_STREAMS_BIDI: P.enc_varint(MAX_STREAMS_BIDI),
            P.TP_MAX_STREAMS_UNI: P.enc_varint(0),
        }
        if retry_scid is not None:
            params[P.TP_RETRY_SCID] = retry_scid
        tp = P.encode_transport_params(params)
        self.tls = T.Tls13Server(listener.certfile, listener.keyfile,
                                 ["mqtt"], tp)
        self._setup_initial_keys(initial_dcid or odcid)
        self._done_sent = False
        self._readers: dict[int, asyncio.StreamReader] = {}
        self._conn_tasks: dict[int, asyncio.Task] = {}

    def _after_tls_progress(self) -> None:
        if self.tls.complete and not self._done_sent:
            self._done_sent = True
            self._out_frames[2].append(F.encode_handshake_done())
            if not self.handshake_done.done():
                self.handshake_done.set_result(True)

    def _on_stream_frame(self, fr: F.Stream) -> None:
        sid = fr.stream_id
        if sid % 4 != 0:       # only client-initiated bidi carries MQTT
            return
        rs = self.streams_rx.get(sid)
        if rs is None:
            if sid // 4 >= MAX_STREAMS_BIDI:
                # enforce the advertised stream limit: unbounded stream
                # ids would spawn unbounded readers/tasks
                self.close(0x04, "stream limit exceeded")
                return
            rs = self.streams_rx[sid] = _RecvStream()
            reader = asyncio.StreamReader()
            self._readers[sid] = reader
            writer = _QuicStreamWriter(self, sid)
            self._conn_tasks[sid] = asyncio.ensure_future(
                self.listener._run_mqtt_connection(reader, writer))
        if not self._enforce_stream_flow(fr, rs):
            return
        data = rs.reassembly.feed(fr.offset, fr.data)
        if fr.fin:
            rs.fin_at = fr.offset + len(fr.data)
        reader = self._readers[sid]
        if data:
            rs.delivered += len(data)
            reader.feed_data(data)
            self._replenish_rx(sid, rs, self.spaces[2])
        if rs.fin_at is not None and rs.reassembly.next >= rs.fin_at:
            reader.feed_eof()

    def _on_closed(self) -> None:
        super()._on_closed()
        for reader in self._readers.values():
            if not reader.at_eof():
                reader.feed_eof()
        self.listener._forget(self)


class QuicListener:
    """UDP endpoint accepting MQTT-over-QUIC connections
    (emqx_listeners.erl quic listener analog)."""

    protocol = "mqtt:quic"

    def __init__(self, node, *, bind: str = "0.0.0.0", port: int = 14567,
                 certfile: str, keyfile: str,
                 zone: Optional[str] = None,
                 max_connections: int = 1024000,
                 retry: bool = False):
        self.node = node
        self.bind = bind
        self.port = port
        self.certfile = certfile
        self.keyfile = keyfile
        self.zone = zone
        self.max_connections = max_connections
        self.current_conns = 0
        # address validation via stateless Retry (RFC 9000 §8.1.2): no
        # connection state exists until the client echoes a valid token
        self.retry = retry
        self._retry_secret = os.urandom(32)
        self._transport: Optional[asyncio.DatagramTransport] = None
        self._conns: dict[bytes, QuicServerConnection] = {}
        self._mqtt_tasks: set[asyncio.Task] = set()

    async def start(self) -> None:
        loop = asyncio.get_running_loop()
        lst = self

        class _Proto(asyncio.DatagramProtocol):
            def connection_made(self, transport):
                lst._transport = transport

            def datagram_received(self, data, addr):
                lst._on_datagram(data, addr)

        self._transport, _ = await loop.create_datagram_endpoint(
            _Proto, local_addr=(self.bind, self.port))
        if self.port == 0:
            self.port = self._transport.get_extra_info("sockname")[1]
        log.info("quic listener started on %s:%d", self.bind, self.port)

    async def stop(self) -> None:
        for conn in list(self._conns.values()):
            conn.close(0, "server shutdown")
        for t in list(self._mqtt_tasks):
            t.cancel()
        if self._mqtt_tasks:
            await asyncio.gather(*self._mqtt_tasks, return_exceptions=True)
        if self._transport:
            self._transport.close()

    def _on_datagram(self, data: bytes, addr) -> None:
        if len(data) < CID_LEN + 1:
            return
        try:
            ptype, dcid, scid, token, _pn, _end = P.peek_header(
                data, 0, CID_LEN)
        except (IndexError, ValueError):
            return
        conn = self._conns.get(dcid)
        if conn is None and ptype == P.PT_INITIAL:
            if self.current_conns >= self.max_connections:
                return
            odcid, retry_scid, validated = dcid, None, False
            if self.retry:
                odcid = self._check_token(token, addr)
                if odcid is None:
                    self._send_retry(dcid, scid, addr)
                    return
                retry_scid, validated = dcid, True
            conn = QuicServerConnection(self, self._transport, addr,
                                        odcid=odcid, client_scid=scid,
                                        initial_dcid=dcid,
                                        retry_scid=retry_scid)
            conn.path_validated = validated
            self.current_conns += 1
            # route future packets by both the incoming DCID (more client
            # Initials) and the server-chosen SCID (handshake/1-RTT)
            conn.route_keys = (dcid, conn.scid)
            self._conns[dcid] = conn
            self._conns[conn.scid] = conn
            conn.start_pto()
        if conn is None:
            return
        # NOTE: the peer address is NOT updated here — the connection
        # adopts a new address only after a packet from it authenticates
        # (RFC 9000 §9; a spoofed datagram with an observed CID must not
        # redirect the server's transmissions)
        try:
            conn.datagram_received(data, addr)
        except Exception:  # noqa: BLE001
            log.exception("quic connection crashed")
            conn.close(1, "internal error")

    # ---- stateless retry tokens --------------------------------------
    def _mint_token(self, odcid: bytes, addr) -> bytes:
        import hashlib
        import hmac
        ts = int(time.time())
        body = ts.to_bytes(8, "big") + bytes([len(odcid)]) + odcid
        mac = hmac.new(self._retry_secret,
                       body + str(addr[0]).encode(),
                       hashlib.sha256).digest()[:16]
        return body + mac

    def _check_token(self, token: bytes, addr,
                     max_age: float = 60.0) -> Optional[bytes]:
        import hashlib
        import hmac
        if len(token) < 9 + 16:
            return None
        ts = int.from_bytes(token[:8], "big")
        olen = token[8]
        if len(token) != 9 + olen + 16:
            return None
        odcid = token[9:9 + olen]
        body, mac = token[:9 + olen], token[9 + olen:]
        want = hmac.new(self._retry_secret,
                        body + str(addr[0]).encode(),
                        hashlib.sha256).digest()[:16]
        if not hmac.compare_digest(mac, want):
            return None
        if abs(time.time() - ts) > max_age:
            return None
        return odcid

    def _send_retry(self, odcid: bytes, client_scid: bytes, addr) -> None:
        new_cid = os.urandom(CID_LEN)
        retry = P.encode_retry(P.QUIC_V1, client_scid, new_cid, odcid,
                               self._mint_token(odcid, addr))
        self._transport.sendto(retry, addr)

    def _forget(self, conn: QuicServerConnection) -> None:
        removed = False
        for key in getattr(conn, "route_keys", (conn.odcid, conn.scid)):
            if self._conns.pop(key, None) is not None:
                removed = True
        if removed:
            self.current_conns -= 1

    async def _run_mqtt_connection(self, reader, writer) -> None:
        from emqx_tpu.broker.connection import Connection
        conn = Connection(self.node, reader, writer, self.zone)
        task = asyncio.current_task()
        self._mqtt_tasks.add(task)
        try:
            await conn.run()
        finally:
            self._mqtt_tasks.discard(task)
