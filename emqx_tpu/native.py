"""ctypes bindings for the native runtime library.

Parity role: SURVEY.md §2.3 — the reference's hot byte paths are native
(BEAM binary matching, jiffy C JSON); here libemqx_native.so provides the
frame scanner, topic hashing, wildcard match, and replayq segment scan,
with pure-Python fallbacks when the library isn't built.

Build with `make -C native` (auto-attempted once on first import when g++
is present); `available()` reports which implementation is active.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
from typing import Optional

log = logging.getLogger("emqx_tpu.native")

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "native")
# EMQX_NATIVE_LIB overrides the library path (sanitizer builds:
# native/Makefile test-asan / test-tsan targets)
_LIB_PATH = os.environ.get("EMQX_NATIVE_LIB") or \
    os.path.join(_NATIVE_DIR, "libemqx_native.so")
if not os.path.isabs(_LIB_PATH):
    _LIB_PATH = os.path.join(os.path.dirname(_NATIVE_DIR), _LIB_PATH)

_lib: Optional[ctypes.CDLL] = None
_tried = False


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    _tried = True
    if not os.path.exists(_LIB_PATH) and os.path.isdir(_NATIVE_DIR):
        try:
            # analysis: ok(loop-affinity) — one-shot bootstrap: builds
            # the missing .so on the FIRST native call of the process
            # (guarded by _tried), before any traffic is flowing; every
            # later call takes the `_lib is not None` fast path above
            subprocess.run(["make", "-C", _NATIVE_DIR], check=True,
                           capture_output=True, timeout=120)
        except (subprocess.SubprocessError, OSError) as e:
            log.info("native build unavailable: %s", e)
    if not os.path.exists(_LIB_PATH):
        return None
    try:
        lib = ctypes.CDLL(_LIB_PATH)
    except OSError as e:
        log.info("native load failed: %s", e)
        return None
    u8p = ctypes.POINTER(ctypes.c_uint8)
    u32p = ctypes.POINTER(ctypes.c_uint32)
    u64p = ctypes.POINTER(ctypes.c_uint64)
    lib.mqtt_frame_scan.restype = ctypes.c_int
    lib.mqtt_frame_scan.argtypes = [
        u8p, ctypes.c_size_t, u32p, u32p, ctypes.c_int, ctypes.c_uint32,
        ctypes.POINTER(ctypes.c_size_t)]
    lib.topic_level_hashes.restype = ctypes.c_int
    lib.topic_level_hashes.argtypes = [
        ctypes.c_char_p, ctypes.c_size_t, u64p, ctypes.c_int]
    lib.topic_hash_batch.restype = ctypes.c_int
    lib.topic_hash_batch.argtypes = [
        ctypes.c_char_p, u32p, u32p, ctypes.c_int, u64p,
        ctypes.POINTER(ctypes.c_uint8), ctypes.c_int]
    lib.topic_match.restype = ctypes.c_int
    lib.topic_match.argtypes = [ctypes.c_char_p, ctypes.c_size_t,
                                ctypes.c_char_p, ctypes.c_size_t]
    lib.mqtt_publish_decode_columnar.restype = ctypes.c_int
    lib.mqtt_publish_decode_columnar.argtypes = [
        u8p, ctypes.c_size_t, u32p, u32p, ctypes.c_int, ctypes.c_int,
        u8p, u8p, u32p, u32p, u32p, u32p, u32p, u32p, u32p]
    lib.replayq_scan.restype = ctypes.c_int
    lib.replayq_scan.argtypes = [u8p, ctypes.c_size_t, u32p, u32p,
                                 ctypes.c_int]
    lib.intern_table_new.restype = ctypes.c_int
    lib.intern_table_new.argtypes = []
    lib.intern_table_free.restype = None
    lib.intern_table_free.argtypes = [ctypes.c_int]
    lib.intern_table_add.restype = ctypes.c_int
    lib.intern_table_add.argtypes = [ctypes.c_int, ctypes.c_char_p,
                                     ctypes.c_uint32, ctypes.c_int32]
    i32p = ctypes.POINTER(ctypes.c_int32)
    lib.topic_encode_batch.restype = ctypes.c_int
    lib.topic_encode_batch.argtypes = [
        ctypes.c_int, ctypes.c_char_p, u32p, u32p, ctypes.c_int,
        ctypes.c_int, ctypes.c_int32, ctypes.c_int32, i32p, i32p,
        ctypes.POINTER(ctypes.c_uint8), ctypes.POINTER(ctypes.c_uint8)]
    _lib = lib
    return _lib


def available() -> bool:
    return _load() is not None


# ---------------------------------------------------------------------
# frame scan
# ---------------------------------------------------------------------
class FrameScanError(Exception):
    pass


_U8P = ctypes.POINTER(ctypes.c_uint8)


def _buf_arg(buf):
    """A ctypes-passable view of any buffer-protocol object WITHOUT
    copying it: writable buffers (bytearray, memoryview of one) go
    through from_buffer; immutable bytes ride the c_char_p fast path
    (CPython passes the object's internal pointer). The pre-ISSUE-11
    bindings did from_buffer_copy, which made every burst scan copy the
    whole read buffer before the C code even ran."""
    if isinstance(buf, bytes):
        return ctypes.cast(ctypes.c_char_p(buf), _U8P)
    try:
        return (ctypes.c_uint8 * len(buf)).from_buffer(buf)
    except (TypeError, ValueError):   # read-only memoryview etc.
        return (ctypes.c_uint8 * len(buf)).from_buffer_copy(buf)


def frame_scan(buf, max_frames: int = 256,
               max_frame_size: int = 0) -> tuple[list[tuple[int, int]],
                                                 int]:
    """Split a byte buffer into complete MQTT frames.

    Accepts any buffer-protocol object (bytes / bytearray / memoryview)
    — bytearray and memoryview are scanned in place, no copy. Returns
    ([(offset, length), ...], consumed). Raises FrameScanError on a
    malformed varint or an oversized frame."""
    lib = _load()
    if lib is None:
        return _frame_scan_py(buf, max_frames, max_frame_size)
    n = len(buf)
    arr = _buf_arg(buf) if n else (ctypes.c_uint8 * 1)()
    off = (ctypes.c_uint32 * max_frames)()
    lens = (ctypes.c_uint32 * max_frames)()
    consumed = ctypes.c_size_t(0)
    rc = lib.mqtt_frame_scan(arr, n, off, lens, max_frames,
                             max_frame_size, ctypes.byref(consumed))
    # release the from_buffer export BEFORE any raise: a traceback
    # holding this frame would otherwise pin the caller's bytearray
    # ("Existing exports of data") through its error handling
    del arr
    if rc == -1:
        raise FrameScanError("malformed varint")
    if rc == -2:
        raise FrameScanError("frame too large")
    return ([(off[i], lens[i]) for i in range(rc)], consumed.value)


def frame_scan_np(buf, max_frames: int = 4096, max_frame_size: int = 0):
    """frame_scan returning numpy arrays — the columnar ingress form:
    (off uint32[n], length uint32[n], consumed). No per-frame tuples,
    no buffer copy. Works with or without the native library (the
    python fallback builds the same arrays)."""
    import numpy as np
    lib = _load()
    if lib is None:
        frames, consumed = _frame_scan_py(buf, max_frames,
                                          max_frame_size)
        off = np.fromiter((f[0] for f in frames), np.uint32,
                          len(frames))
        lens = np.fromiter((f[1] for f in frames), np.uint32,
                           len(frames))
        return off, lens, consumed
    n = len(buf)
    arr = _buf_arg(buf) if n else (ctypes.c_uint8 * 1)()
    off = np.empty(max_frames, np.uint32)
    lens = np.empty(max_frames, np.uint32)
    consumed = ctypes.c_size_t(0)
    u32p = ctypes.POINTER(ctypes.c_uint32)
    rc = lib.mqtt_frame_scan(arr, n, off.ctypes.data_as(u32p),
                             lens.ctypes.data_as(u32p), max_frames,
                             max_frame_size, ctypes.byref(consumed))
    del arr   # release the buffer export before any raise (see above)
    if rc == -1:
        raise FrameScanError("malformed varint")
    if rc == -2:
        raise FrameScanError("frame too large")
    return off[:rc], lens[:rc], consumed.value


def _frame_scan_py(buf: bytes, max_frames: int,
                   max_frame_size: int) -> tuple[list[tuple[int, int]],
                                                 int]:
    out: list[tuple[int, int]] = []
    pos = 0
    consumed = 0
    while pos + 2 <= len(buf) and len(out) < max_frames:
        p = pos + 1
        rem = 0
        mult = 1
        nbytes = 0
        complete = False
        while p < len(buf) and nbytes < 4:
            b = buf[p]
            p += 1
            rem += (b & 0x7F) * mult
            mult <<= 7
            nbytes += 1
            if not b & 0x80:
                complete = True
                break
        if not complete:
            if nbytes >= 4:
                raise FrameScanError("malformed varint")
            break
        total = (p - pos) + rem
        if max_frame_size and total > max_frame_size:
            raise FrameScanError("frame too large")
        if pos + total > len(buf):
            break
        out.append((pos, total))
        pos += total
        consumed = pos
    return out, consumed


# ---------------------------------------------------------------------
# columnar PUBLISH decode (ISSUE 11)
# ---------------------------------------------------------------------
def publish_decode_columnar(buf, off, lens, v5: bool):
    """Decode all PUBLISH frames among the scanned boundaries in one
    pass. `off`/`lens` are the uint32 numpy arrays from frame_scan_np;
    returns a dict of parallel numpy arrays:

        kind        uint8[n]   1 = columnar-decoded PUBLISH; 0 = hand
                               this frame to the strict per-packet
                               parser (non-PUBLISH, or a PUBLISH the
                               strict parser must reject precisely)
        flags       uint8[n]   fixed-header nibble (bit0 retain,
                               bits1-2 qos, bit3 dup)
        topic_off / topic_len / packet_id / props_off / props_len /
        payload_off / payload_len          uint32[n], absolute into buf

    kind=0 rows are all-zero in every other array, native and fallback
    alike — the differential fuzz suite compares them array-for-array.
    UTF-8 topic validation and v5 property-content parsing stay with
    the caller (it owns the resulting python objects)."""
    import numpy as np
    n = len(off)
    out = {
        "kind": np.zeros(n, np.uint8),
        "flags": np.zeros(n, np.uint8),
        "topic_off": np.zeros(n, np.uint32),
        "topic_len": np.zeros(n, np.uint32),
        "packet_id": np.zeros(n, np.uint32),
        "props_off": np.zeros(n, np.uint32),
        "props_len": np.zeros(n, np.uint32),
        "payload_off": np.zeros(n, np.uint32),
        "payload_len": np.zeros(n, np.uint32),
    }
    if n == 0:
        return out
    lib = _load()
    if lib is None:
        return _publish_decode_columnar_py(buf, off, lens, v5, out)
    off = np.ascontiguousarray(off, np.uint32)
    lens = np.ascontiguousarray(lens, np.uint32)
    u32p = ctypes.POINTER(ctypes.c_uint32)
    u8p = ctypes.POINTER(ctypes.c_uint8)
    lib.mqtt_publish_decode_columnar(
        _buf_arg(buf), len(buf), off.ctypes.data_as(u32p),
        lens.ctypes.data_as(u32p), n, 1 if v5 else 0,
        out["kind"].ctypes.data_as(u8p),
        out["flags"].ctypes.data_as(u8p),
        out["topic_off"].ctypes.data_as(u32p),
        out["topic_len"].ctypes.data_as(u32p),
        out["packet_id"].ctypes.data_as(u32p),
        out["props_off"].ctypes.data_as(u32p),
        out["props_len"].ctypes.data_as(u32p),
        out["payload_off"].ctypes.data_as(u32p),
        out["payload_len"].ctypes.data_as(u32p))
    return out


def _publish_decode_columnar_py(buf, off, lens, v5: bool, out):
    """Pure-python mirror of the C decoder — bit-identical semantics
    (the repo's established fallback-parity pattern; the differential
    fuzz suite asserts array equality against the native build)."""
    kind = out["kind"]
    flags = out["flags"]
    topic_off = out["topic_off"]
    topic_len = out["topic_len"]
    packet_id = out["packet_id"]
    props_off = out["props_off"]
    props_len = out["props_len"]
    payload_off = out["payload_off"]
    payload_len = out["payload_len"]
    blen = len(buf)
    for i in range(len(off)):
        s = int(off[i])
        e = s + int(lens[i])
        if e > blen or lens[i] < 2:
            continue
        b0 = buf[s]
        if (b0 >> 4) != 3:
            continue
        qos = (b0 >> 1) & 0x3
        if qos == 3:
            continue
        p = s + 1
        nb = 0
        while p < e and nb < 4:
            b = buf[p]
            p += 1
            nb += 1
            if not (b & 0x80):
                break
        if p + 2 > e:
            continue
        tl = (buf[p] << 8) | buf[p + 1]
        p += 2
        if p + tl > e:
            continue
        t_off = p
        p += tl
        pid = 0
        if qos > 0:
            if p + 2 > e:
                continue
            pid = (buf[p] << 8) | buf[p + 1]
            p += 2
            if pid == 0:
                continue
        pr_off = pr_len = 0
        if v5:
            pl, mult, k, done = 0, 1, 0, False
            while p < e and k < 4:
                b = buf[p]
                p += 1
                pl += (b & 0x7F) * mult
                mult <<= 7
                k += 1
                if not (b & 0x80):
                    done = True
                    break
            if not done:
                continue
            if p + pl > e:
                continue
            pr_off, pr_len = p, pl
            p += pl
        topic_off[i] = t_off
        topic_len[i] = tl
        packet_id[i] = pid
        props_off[i] = pr_off
        props_len[i] = pr_len
        payload_off[i] = p
        payload_len[i] = e - p
        flags[i] = b0 & 0x0F
        kind[i] = 1
    return out


# ---------------------------------------------------------------------
# topic hashing
# ---------------------------------------------------------------------
def _fnv1a_py(s: bytes) -> int:
    h = 1469598103934665603
    for b in s:
        h = ((h ^ b) * 1099511628211) & 0xFFFFFFFFFFFFFFFF
    return h


def topic_hashes(topic: str, max_levels: int = 16) -> list[int]:
    """Per-level FNV-1a-64 hashes (the intern-table key function)."""
    lib = _load()
    raw = topic.encode()
    if lib is None:
        return [_fnv1a_py(w) for w in raw.split(b"/")[:max_levels]]
    out = (ctypes.c_uint64 * max_levels)()
    n = lib.topic_level_hashes(raw, len(raw), out, max_levels)
    if n < 0:
        return [_fnv1a_py(w) for w in raw.split(b"/")[:max_levels]]
    return list(out[:n])


def topic_hashes_batch(topics: list[str],
                       max_levels: int = 16) -> list[list[int]]:
    lib = _load()
    if lib is None or not topics:
        return [topic_hashes(t, max_levels) for t in topics]
    raws = [t.encode() for t in topics]
    buf = b"".join(raws)
    offs = (ctypes.c_uint32 * len(raws))()
    lens = (ctypes.c_uint32 * len(raws))()
    pos = 0
    for i, r in enumerate(raws):
        offs[i] = pos
        lens[i] = len(r)
        pos += len(r)
    out = (ctypes.c_uint64 * (len(raws) * max_levels))()
    counts = (ctypes.c_uint8 * len(raws))()
    lib.topic_hash_batch(buf, offs, lens, len(raws), out, counts,
                         max_levels)
    res = []
    for i, t in enumerate(topics):
        if counts[i] == 0xFF:       # deeper than max_levels: fallback
            res.append(topic_hashes(t, max_levels))
        else:
            base = i * max_levels
            res.append(list(out[base:base + counts[i]]))
    return res


# ---------------------------------------------------------------------
# wildcard match
# ---------------------------------------------------------------------
def topic_match(name: str, filter_: str) -> bool:
    lib = _load()
    if lib is None:
        from emqx_tpu.utils import topic as T
        return T.match(name, filter_)
    nb, fb = name.encode(), filter_.encode()
    return bool(lib.topic_match(nb, len(nb), fb, len(fb)))


# ---------------------------------------------------------------------
# interned-word mirror + batched topic encode (SURVEY §7 hard-part 3)
# ---------------------------------------------------------------------
def intern_mirror_new() -> Optional[int]:
    """Allocate a native word→id mirror; None when unavailable."""
    lib = _load()
    if lib is None:
        return None
    h = lib.intern_table_new()
    return h if h >= 0 else None


def intern_mirror_free(h: int) -> None:
    lib = _load()
    if lib is not None and h is not None and h >= 0:
        lib.intern_table_free(h)


def intern_mirror_add(h: int, word: str, wid: int) -> bool:
    """Mirror one word→id. The C table stores the word BYTES and
    confirms lookups with memcmp, so hash collisions between different
    words are handled by probing, not by failure; False only on
    allocation failure, a dead handle, or an id conflict for the SAME
    word (a caller bug) — the caller retires the mirror then."""
    lib = _load()
    raw = word.encode()
    return lib.intern_table_add(h, raw, len(raw), wid) == 0


def topic_encode_batch(h: int, topics: list, max_levels: int,
                       unknown_id: int, pad_id: int):
    """Encode publish topics in one native call. Returns numpy arrays
    (ids [n, L] int32, lens [n] int32, dollar [n] bool, too_long [n]
    bool), or None when the library/handle is unavailable."""
    lib = _load()
    if lib is None or h is None or not topics:
        return None
    import numpy as np
    raws = [t.encode() for t in topics]
    buf = b"".join(raws)
    n = len(raws)
    offs = np.zeros(n, np.uint32)
    lens_in = np.fromiter((len(r) for r in raws), np.uint32, n)
    if n > 1:
        np.cumsum(lens_in[:-1], out=offs[1:])
    ids = np.empty((n, max_levels), np.int32)
    lens = np.empty(n, np.int32)
    dollar = np.empty(n, np.uint8)
    toolong = np.empty(n, np.uint8)
    u32p = ctypes.POINTER(ctypes.c_uint32)
    i32p = ctypes.POINTER(ctypes.c_int32)
    u8p = ctypes.POINTER(ctypes.c_uint8)
    rc = lib.topic_encode_batch(
        h, buf, offs.ctypes.data_as(u32p),
        lens_in.ctypes.data_as(u32p), n, max_levels,
        unknown_id, pad_id, ids.ctypes.data_as(i32p),
        lens.ctypes.data_as(i32p), dollar.ctypes.data_as(u8p),
        toolong.ctypes.data_as(u8p))
    if rc != n:
        return None
    return ids, lens, dollar.astype(bool), toolong.astype(bool)


# ---------------------------------------------------------------------
# replayq segment scan
# ---------------------------------------------------------------------
def replayq_scan(data: bytes, max_items: int = 65536
                 ) -> list[tuple[int, int]]:
    """(offset, length) of each complete length-prefixed item."""
    lib = _load()
    if lib is None:
        out = []
        i = 0
        while i + 4 <= len(data) and len(out) < max_items:
            n = int.from_bytes(data[i:i + 4], "big")
            if i + 4 + n > len(data):
                break
            out.append((i + 4, n))
            i += 4 + n
        return out
    arr = _buf_arg(data) if data else (ctypes.c_uint8 * 1)()
    off = (ctypes.c_uint32 * max_items)()
    lens = (ctypes.c_uint32 * max_items)()
    rc = lib.replayq_scan(arr, len(data), off, lens, max_items)
    return [(off[i], lens[i]) for i in range(rc)]
