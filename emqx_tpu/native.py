"""ctypes bindings for the native runtime library.

Parity role: SURVEY.md §2.3 — the reference's hot byte paths are native
(BEAM binary matching, jiffy C JSON); here libemqx_native.so provides the
frame scanner, topic hashing, wildcard match, and replayq segment scan,
with pure-Python fallbacks when the library isn't built.

Build with `make -C native` (auto-attempted once on first import when g++
is present); `available()` reports which implementation is active.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
from typing import Optional

log = logging.getLogger("emqx_tpu.native")

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "native")
# EMQX_NATIVE_LIB overrides the library path (sanitizer builds:
# native/Makefile test-asan / test-tsan targets)
_LIB_PATH = os.environ.get("EMQX_NATIVE_LIB") or \
    os.path.join(_NATIVE_DIR, "libemqx_native.so")
if not os.path.isabs(_LIB_PATH):
    _LIB_PATH = os.path.join(os.path.dirname(_NATIVE_DIR), _LIB_PATH)

_lib: Optional[ctypes.CDLL] = None
_tried = False


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    _tried = True
    if not os.path.exists(_LIB_PATH) and os.path.isdir(_NATIVE_DIR):
        try:
            subprocess.run(["make", "-C", _NATIVE_DIR], check=True,
                           capture_output=True, timeout=120)
        except (subprocess.SubprocessError, OSError) as e:
            log.info("native build unavailable: %s", e)
    if not os.path.exists(_LIB_PATH):
        return None
    try:
        lib = ctypes.CDLL(_LIB_PATH)
    except OSError as e:
        log.info("native load failed: %s", e)
        return None
    u8p = ctypes.POINTER(ctypes.c_uint8)
    u32p = ctypes.POINTER(ctypes.c_uint32)
    u64p = ctypes.POINTER(ctypes.c_uint64)
    lib.mqtt_frame_scan.restype = ctypes.c_int
    lib.mqtt_frame_scan.argtypes = [
        u8p, ctypes.c_size_t, u32p, u32p, ctypes.c_int, ctypes.c_uint32,
        ctypes.POINTER(ctypes.c_size_t)]
    lib.topic_level_hashes.restype = ctypes.c_int
    lib.topic_level_hashes.argtypes = [
        ctypes.c_char_p, ctypes.c_size_t, u64p, ctypes.c_int]
    lib.topic_hash_batch.restype = ctypes.c_int
    lib.topic_hash_batch.argtypes = [
        ctypes.c_char_p, u32p, u32p, ctypes.c_int, u64p,
        ctypes.POINTER(ctypes.c_uint8), ctypes.c_int]
    lib.topic_match.restype = ctypes.c_int
    lib.topic_match.argtypes = [ctypes.c_char_p, ctypes.c_size_t,
                                ctypes.c_char_p, ctypes.c_size_t]
    lib.replayq_scan.restype = ctypes.c_int
    lib.replayq_scan.argtypes = [u8p, ctypes.c_size_t, u32p, u32p,
                                 ctypes.c_int]
    lib.intern_table_new.restype = ctypes.c_int
    lib.intern_table_new.argtypes = []
    lib.intern_table_free.restype = None
    lib.intern_table_free.argtypes = [ctypes.c_int]
    lib.intern_table_add.restype = ctypes.c_int
    lib.intern_table_add.argtypes = [ctypes.c_int, ctypes.c_char_p,
                                     ctypes.c_uint32, ctypes.c_int32]
    i32p = ctypes.POINTER(ctypes.c_int32)
    lib.topic_encode_batch.restype = ctypes.c_int
    lib.topic_encode_batch.argtypes = [
        ctypes.c_int, ctypes.c_char_p, u32p, u32p, ctypes.c_int,
        ctypes.c_int, ctypes.c_int32, ctypes.c_int32, i32p, i32p,
        ctypes.POINTER(ctypes.c_uint8), ctypes.POINTER(ctypes.c_uint8)]
    _lib = lib
    return _lib


def available() -> bool:
    return _load() is not None


# ---------------------------------------------------------------------
# frame scan
# ---------------------------------------------------------------------
class FrameScanError(Exception):
    pass


def frame_scan(buf: bytes, max_frames: int = 256,
               max_frame_size: int = 0) -> tuple[list[tuple[int, int]],
                                                 int]:
    """Split a byte buffer into complete MQTT frames.

    Returns ([(offset, length), ...], consumed). Raises FrameScanError on
    a malformed varint or an oversized frame."""
    lib = _load()
    if lib is None:
        return _frame_scan_py(buf, max_frames, max_frame_size)
    n = len(buf)
    arr = (ctypes.c_uint8 * n).from_buffer_copy(buf) if n else \
        (ctypes.c_uint8 * 1)()
    off = (ctypes.c_uint32 * max_frames)()
    lens = (ctypes.c_uint32 * max_frames)()
    consumed = ctypes.c_size_t(0)
    rc = lib.mqtt_frame_scan(arr, n, off, lens, max_frames,
                             max_frame_size, ctypes.byref(consumed))
    if rc == -1:
        raise FrameScanError("malformed varint")
    if rc == -2:
        raise FrameScanError("frame too large")
    return ([(off[i], lens[i]) for i in range(rc)], consumed.value)


def _frame_scan_py(buf: bytes, max_frames: int,
                   max_frame_size: int) -> tuple[list[tuple[int, int]],
                                                 int]:
    out: list[tuple[int, int]] = []
    pos = 0
    consumed = 0
    while pos + 2 <= len(buf) and len(out) < max_frames:
        p = pos + 1
        rem = 0
        mult = 1
        nbytes = 0
        complete = False
        while p < len(buf) and nbytes < 4:
            b = buf[p]
            p += 1
            rem += (b & 0x7F) * mult
            mult <<= 7
            nbytes += 1
            if not b & 0x80:
                complete = True
                break
        if not complete:
            if nbytes >= 4:
                raise FrameScanError("malformed varint")
            break
        total = (p - pos) + rem
        if max_frame_size and total > max_frame_size:
            raise FrameScanError("frame too large")
        if pos + total > len(buf):
            break
        out.append((pos, total))
        pos += total
        consumed = pos
    return out, consumed


# ---------------------------------------------------------------------
# topic hashing
# ---------------------------------------------------------------------
def _fnv1a_py(s: bytes) -> int:
    h = 1469598103934665603
    for b in s:
        h = ((h ^ b) * 1099511628211) & 0xFFFFFFFFFFFFFFFF
    return h


def topic_hashes(topic: str, max_levels: int = 16) -> list[int]:
    """Per-level FNV-1a-64 hashes (the intern-table key function)."""
    lib = _load()
    raw = topic.encode()
    if lib is None:
        return [_fnv1a_py(w) for w in raw.split(b"/")[:max_levels]]
    out = (ctypes.c_uint64 * max_levels)()
    n = lib.topic_level_hashes(raw, len(raw), out, max_levels)
    if n < 0:
        return [_fnv1a_py(w) for w in raw.split(b"/")[:max_levels]]
    return list(out[:n])


def topic_hashes_batch(topics: list[str],
                       max_levels: int = 16) -> list[list[int]]:
    lib = _load()
    if lib is None or not topics:
        return [topic_hashes(t, max_levels) for t in topics]
    raws = [t.encode() for t in topics]
    buf = b"".join(raws)
    offs = (ctypes.c_uint32 * len(raws))()
    lens = (ctypes.c_uint32 * len(raws))()
    pos = 0
    for i, r in enumerate(raws):
        offs[i] = pos
        lens[i] = len(r)
        pos += len(r)
    out = (ctypes.c_uint64 * (len(raws) * max_levels))()
    counts = (ctypes.c_uint8 * len(raws))()
    lib.topic_hash_batch(buf, offs, lens, len(raws), out, counts,
                         max_levels)
    res = []
    for i, t in enumerate(topics):
        if counts[i] == 0xFF:       # deeper than max_levels: fallback
            res.append(topic_hashes(t, max_levels))
        else:
            base = i * max_levels
            res.append(list(out[base:base + counts[i]]))
    return res


# ---------------------------------------------------------------------
# wildcard match
# ---------------------------------------------------------------------
def topic_match(name: str, filter_: str) -> bool:
    lib = _load()
    if lib is None:
        from emqx_tpu.utils import topic as T
        return T.match(name, filter_)
    nb, fb = name.encode(), filter_.encode()
    return bool(lib.topic_match(nb, len(nb), fb, len(fb)))


# ---------------------------------------------------------------------
# interned-word mirror + batched topic encode (SURVEY §7 hard-part 3)
# ---------------------------------------------------------------------
def intern_mirror_new() -> Optional[int]:
    """Allocate a native word→id mirror; None when unavailable."""
    lib = _load()
    if lib is None:
        return None
    h = lib.intern_table_new()
    return h if h >= 0 else None


def intern_mirror_free(h: int) -> None:
    lib = _load()
    if lib is not None and h is not None and h >= 0:
        lib.intern_table_free(h)


def intern_mirror_add(h: int, word: str, wid: int) -> bool:
    """Mirror one word→id. The C table stores the word BYTES and
    confirms lookups with memcmp, so hash collisions between different
    words are handled by probing, not by failure; False only on
    allocation failure, a dead handle, or an id conflict for the SAME
    word (a caller bug) — the caller retires the mirror then."""
    lib = _load()
    raw = word.encode()
    return lib.intern_table_add(h, raw, len(raw), wid) == 0


def topic_encode_batch(h: int, topics: list, max_levels: int,
                       unknown_id: int, pad_id: int):
    """Encode publish topics in one native call. Returns numpy arrays
    (ids [n, L] int32, lens [n] int32, dollar [n] bool, too_long [n]
    bool), or None when the library/handle is unavailable."""
    lib = _load()
    if lib is None or h is None or not topics:
        return None
    import numpy as np
    raws = [t.encode() for t in topics]
    buf = b"".join(raws)
    n = len(raws)
    offs = np.zeros(n, np.uint32)
    lens_in = np.fromiter((len(r) for r in raws), np.uint32, n)
    if n > 1:
        np.cumsum(lens_in[:-1], out=offs[1:])
    ids = np.empty((n, max_levels), np.int32)
    lens = np.empty(n, np.int32)
    dollar = np.empty(n, np.uint8)
    toolong = np.empty(n, np.uint8)
    u32p = ctypes.POINTER(ctypes.c_uint32)
    i32p = ctypes.POINTER(ctypes.c_int32)
    u8p = ctypes.POINTER(ctypes.c_uint8)
    rc = lib.topic_encode_batch(
        h, buf, offs.ctypes.data_as(u32p),
        lens_in.ctypes.data_as(u32p), n, max_levels,
        unknown_id, pad_id, ids.ctypes.data_as(i32p),
        lens.ctypes.data_as(i32p), dollar.ctypes.data_as(u8p),
        toolong.ctypes.data_as(u8p))
    if rc != n:
        return None
    return ids, lens, dollar.astype(bool), toolong.astype(bool)


# ---------------------------------------------------------------------
# replayq segment scan
# ---------------------------------------------------------------------
def replayq_scan(data: bytes, max_items: int = 65536
                 ) -> list[tuple[int, int]]:
    """(offset, length) of each complete length-prefixed item."""
    lib = _load()
    if lib is None:
        out = []
        i = 0
        while i + 4 <= len(data) and len(out) < max_items:
            n = int.from_bytes(data[i:i + 4], "big")
            if i + 4 + n > len(data):
                break
            out.append((i + 4, n))
            i += 4 + n
        return out
    arr = (ctypes.c_uint8 * len(data)).from_buffer_copy(data) \
        if data else (ctypes.c_uint8 * 1)()
    off = (ctypes.c_uint32 * max_items)()
    lens = (ctypes.c_uint32 * max_items)()
    rc = lib.replayq_scan(arr, len(data), off, lens, max_items)
    return [(off[i], lens[i]) for i in range(rc)]
