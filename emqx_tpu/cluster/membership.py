"""Cluster membership: the ekka analog.

Parity: ekka (started at emqx_app.erl:51) + discovery/autoheal/autoclean
config (emqx_machine_schema.erl:66-111). Discovery strategies: `manual`
(explicit join/leave) and `static` (seed address list) — the dns/etcd/k8s
strategies of the reference are address providers feeding the same join path
and are pluggable via `seeds_fn`.

Failure detection: periodic heartbeats over the RPC plane; a peer missing
`max_missed` beats is declared down (nodedown event -> route cleanup in
cluster.py, the emqx_router_helper analog, §3.5). A downed node that beats
again is healed (autoheal analog); `autoclean_s` removes long-dead members.
"""

from __future__ import annotations

import asyncio
import logging
import time
from typing import Callable, Optional

from emqx_tpu.cluster.rpc import RpcError, RpcNode

log = logging.getLogger("emqx_tpu.cluster.membership")


class Membership:
    def __init__(self, rpc: RpcNode, *,
                 heartbeat_s: float = 1.0, max_missed: int = 3,
                 autoclean_s: float = 300.0,
                 seeds: Optional[list[tuple[str, int]]] = None):
        self.rpc = rpc
        self.heartbeat_s = heartbeat_s
        self.max_missed = max_missed
        self.autoclean_s = autoclean_s
        self.seeds = seeds or []
        # boot incarnation, gossiped with our address: lets receivers
        # (a) ignore STALE gossip that would re-point a working peer pool
        # back at a dead address, and (b) detect a restart even when it
        # happened inside the failure-detection window (nodedown never
        # fired) — the restart emits "healed" so the store resyncs the
        # fresh incarnation's state
        self.inc = time.time_ns()
        # node -> {"addr": (host,port), "status": running|down,
        #          "last": ts, "inc": peer boot incarnation or None}
        self.members: dict[str, dict] = {
            rpc.node: {"addr": rpc.address, "status": "running",
                       "last": time.time(), "inc": self.inc}}
        self._watchers: list[Callable[[str, str], None]] = []
        self._task: Optional[asyncio.Task] = None
        rpc.register("ekka.heartbeat", self._h_heartbeat)
        rpc.register("ekka.join", self._h_join)
        rpc.register("ekka.members", self._h_members)
        rpc.register("ekka.leave", self._h_leave)

    # ---- events ----
    def monitor(self, fn: Callable[[str, str], None]) -> None:
        """fn(event, node) with event in nodeup|nodedown|nodeleft|healed."""
        self._watchers.append(fn)

    def _emit(self, event: str, node: str) -> None:
        for fn in self._watchers:
            try:
                fn(event, node)
            except Exception:  # noqa: BLE001
                log.exception("membership watcher failed")

    # ---- local view ----
    def running_nodes(self) -> list[str]:
        return sorted(n for n, m in self.members.items()
                      if m["status"] == "running")

    def other_nodes(self) -> list[str]:
        return [n for n in self.running_nodes() if n != self.rpc.node]

    def is_running(self, node: str) -> bool:
        m = self.members.get(node)
        return bool(m and m["status"] == "running")

    def info(self) -> dict:
        return {n: {"status": m["status"],
                    "addr": list(m["addr"])} for n, m in self.members.items()}

    # ---- join/leave (emqx_mgmt_cli cluster join/leave analog) ----
    async def start(self) -> None:
        # re-read the address: port 0 resolves when the rpc server binds
        self.members[self.rpc.node]["addr"] = self.rpc.address
        self._task = asyncio.create_task(self._beat_loop())
        for host, port in self.seeds:
            if (host, port) == self.rpc.address:
                continue
            try:
                await self.join_addr(host, port)
            except RpcError:
                log.info("seed %s:%s unreachable at boot", host, port)

    async def join_addr(self, host: str, port: int) -> None:
        """Join the cluster a seed node belongs to."""
        probe = f"probe@{host}:{port}"
        self.rpc.add_peer(probe, host, port)
        try:
            view = await self.rpc.call(probe, "ekka.join", [
                self.rpc.node, list(self.rpc.address), self._view()])
        finally:
            await self.rpc.drop_peer(probe)
        self._merge_view(view)

    async def _h_join(self, node: str, addr: list, view: dict) -> dict:
        self._add_member(node, tuple(addr))
        self._merge_view(view)
        # gossip the new member to everyone we know
        for n in self.other_nodes():
            if n != node:
                await self.rpc.cast(n, "ekka.members", [self._view()])
        return self._view()

    async def _h_members(self, view: dict) -> None:
        self._merge_view(view)

    async def leave(self) -> None:
        """This node leaves the cluster."""
        for n in self.other_nodes():
            await self.rpc.cast(n, "ekka.leave", [self.rpc.node])
        self.members = {self.rpc.node: self.members[self.rpc.node]}

    async def _h_leave(self, node: str) -> None:
        if self.members.pop(node, None) is not None:
            await self.rpc.drop_peer(node)
            self._emit("nodeleft", node)

    async def force_leave(self, node: str) -> None:
        """Evict a member cluster-wide (cluster force-leave CLI)."""
        for n in self.other_nodes():
            await self.rpc.cast(n, "ekka.leave", [node])
        await self._h_leave(node)

    def _view(self) -> dict:
        self.members[self.rpc.node]["addr"] = self.rpc.address
        return {n: {"addr": list(m["addr"]), "status": m["status"],
                    "inc": m.get("inc")}
                for n, m in self.members.items()}

    def _merge_view(self, view: dict) -> None:
        for node, m in view.items():
            self._add_member(node, tuple(m["addr"]), m.get("inc"))

    def _add_member(self, node: str, addr: tuple,
                    inc: Optional[int] = None) -> None:
        if node == self.rpc.node:
            return
        known = self.members.get(node)
        known_inc = known.get("inc") if known else None
        if (inc is not None and known_inc is not None
                and inc < known_inc):
            # STALE gossip about a dead incarnation: acting on it would
            # re-point a working peer pool at the corpse address
            return
        restarted = (inc is not None and known_inc is not None
                     and inc > known_inc)
        self.rpc.add_peer(node, addr[0], addr[1])
        if known is None or known["status"] != "running" or restarted:
            self.members[node] = {"addr": addr, "status": "running",
                                  "last": time.time(),
                                  "inc": inc if inc is not None
                                  else known_inc}
            # a restart INSIDE the failure-detection window never fires
            # nodedown; the incarnation bump is the only restart signal,
            # and "healed" makes the store resync (purging the dead
            # incarnation's rows even if the fresh node stays idle)
            self._emit("healed" if known else "nodeup", node)
        else:
            if inc is not None:
                known["inc"] = inc
            if known["addr"] != addr:
                # same incarnation at a new address cannot really happen;
                # legacy/inc-less gossip keeps last-writer-wins behavior
                known["addr"] = addr

    # ---- heartbeat / failure detection ----
    async def _beat_loop(self) -> None:
        while True:
            await asyncio.sleep(self.heartbeat_s)
            now = time.time()
            # probe down members too: a mutual partition where both sides
            # marked each other down must still heal once the network does
            for node, m in list(self.members.items()):
                if node == self.rpc.node:
                    continue
                try:
                    # heartbeats carry the full view both ways: missed
                    # join-time gossip heals on the next beat
                    rview = await self.rpc.call(
                        node, "ekka.heartbeat",
                        [self.rpc.node, self._view()],
                        timeout=self.heartbeat_s * 2)
                    m["last"] = now
                    if m["status"] == "down":
                        m["status"] = "running"
                        self._emit("healed", node)
                    if isinstance(rview, dict):
                        self._merge_view(rview)
                except RpcError:
                    pass
            self._check_down(now)

    def _check_down(self, now: float) -> None:
        for node, m in list(self.members.items()):
            if node == self.rpc.node:
                continue
            silent = now - m["last"]
            if (m["status"] == "running"
                    and silent > self.heartbeat_s * self.max_missed):
                m["status"] = "down"
                self._emit("nodedown", node)
            elif m["status"] == "down" and silent > self.autoclean_s:
                del self.members[node]   # cluster_autoclean
                self._emit("nodeleft", node)

    async def _h_heartbeat(self, from_node: str,
                           view: Optional[dict] = None) -> dict:
        if view:
            self._merge_view(view)   # learns unknown senders/members too
        m = self.members.get(from_node)
        if m is not None:
            m["last"] = time.time()
            if m["status"] == "down":   # autoheal: it came back
                m["status"] = "running"
                self._emit("healed", from_node)
        return self._view()

    async def stop(self) -> None:
        if self._task:
            self._task.cancel()
