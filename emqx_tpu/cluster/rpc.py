"""Data-plane RPC: the gen_rpc analog.

Parity: emqx_rpc.erl:20-60 — per-peer pool of `tcp_client_num` TCP channels,
per-key channel pinning via hash to preserve per-topic ordering, sync `call`
vs async `cast`. Here: asyncio TCP with length-prefixed JSON frames and a
shared-cookie handshake (the Erlang-distribution cookie analog).

This is the host-side DCN path of the TPU design (SURVEY.md §5.8): intra-chip
fan-out happens on device via collectives; cross-host forwarding rides these
key-pinned streams so per-topic order is preserved end to end.

Wire frame: 4-byte big-endian length + JSON object. Bytes values are encoded
as {"$b": base64}. Messages:
  {"t":"hello","node":...,"cookie":...}      handshake (first frame)
  {"t":"call","id":N,"fn":...,"args":[...]}  sync request
  {"t":"reply","id":N,"ok":bool,"val":...}   response
  {"t":"cast","fn":...,"args":[...]}         async, no response
"""

from __future__ import annotations

import asyncio

from emqx_tpu.broker.supervise import spawn
from emqx_tpu.utils.aio import timeout_after
import base64
import json
import logging
from typing import Any, Awaitable, Callable, Optional

log = logging.getLogger("emqx_tpu.cluster.rpc")

DEFAULT_CHANNELS = 4          # gen_rpc tcp_client_num default is 1; we pin 4
CALL_TIMEOUT = 10.0
CONNECT_TIMEOUT = 5.0         # TCP connect + hello handshake bound: a
# FROZEN peer (SIGSTOP — gray failure) accepts TCP and then never answers
# the hello; an unbounded handshake parked the heartbeat loop, so
# failure detection never fired and every caller waited its full budget


class RpcError(Exception):
    """badrpc analog (emqx_rpc.erl filters {badrpc,_} / {badtcp,_})."""


def _enc(obj: Any) -> Any:
    if isinstance(obj, (bytes, bytearray)):
        return {"$b": base64.b64encode(bytes(obj)).decode()}
    if isinstance(obj, dict):
        return {k: _enc(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_enc(v) for v in obj]
    if isinstance(obj, set):
        return {"$set": [_enc(v) for v in sorted(obj, key=repr)]}
    return obj


def _dec(obj: Any) -> Any:
    if isinstance(obj, dict):
        if "$b" in obj and len(obj) == 1:
            return base64.b64decode(obj["$b"])
        if "$set" in obj and len(obj) == 1:
            return set(_dec(v) for v in obj["$set"])
        return {k: _dec(v) for k, v in obj.items()}
    if isinstance(obj, list):
        return [_dec(v) for v in obj]
    return obj


def encode_frame(msg: dict) -> bytes:
    body = json.dumps(_enc(msg), separators=(",", ":")).encode()
    return len(body).to_bytes(4, "big") + body


async def read_frame(reader: asyncio.StreamReader,
                     max_len: int = 64 << 20) -> Optional[dict]:
    try:
        hdr = await reader.readexactly(4)
    except (asyncio.IncompleteReadError, ConnectionError):
        return None
    n = int.from_bytes(hdr, "big")
    if n > max_len:
        raise RpcError(f"frame too large: {n}")
    try:
        body = await reader.readexactly(n)
    except (asyncio.IncompleteReadError, ConnectionError):
        return None
    return _dec(json.loads(body))


class _Channel:
    """One outbound TCP connection to a peer; serialized writes keep
    per-channel ordering (the gen_rpc per-key stream)."""

    def __init__(self, host: str, port: int, node: str, cookie: str):
        self.host, self.port = host, port
        self.node, self.cookie = node, cookie
        self.reader: Optional[asyncio.StreamReader] = None
        self.writer: Optional[asyncio.StreamWriter] = None
        self._pending: dict[int, asyncio.Future] = {}
        self._next_id = 0
        self._lock = asyncio.Lock()
        self._reader_task: Optional[asyncio.Task] = None

    async def connect(self) -> None:
        if self._reader_task:      # stale reader from a dead connection must
            self._reader_task.cancel()   # not fail the new one's futures
            self._reader_task = None
        # abandoning the old connection means its in-flight calls can
        # never be answered: fail them NOW. (The cancelled stale reader
        # skips its own cleanup via the current-task guard, so without
        # this, a racing call whose send beat the reconnect would park
        # for its full timeout when this connect() fails.)
        self._fail_pending(RpcError("connection closed"))
        try:
            # 3.10-compatible deadline (asyncio.timeout is 3.11+;
            # utils.aio.timeout_after converts only OUR deadline
            # cancel into TimeoutError)
            async with timeout_after(CONNECT_TIMEOUT):
                self.reader, self.writer = await asyncio.open_connection(
                    self.host, self.port)
                self.writer.write(encode_frame(
                    {"t": "hello", "node": self.node,
                     "cookie": self.cookie}))
                await self.writer.drain()
                ack = await read_frame(self.reader)
            if not ack or ack.get("t") != "hello_ok":
                raise RpcError(
                    f"handshake rejected by {self.host}:{self.port}")
            self._reader_task = asyncio.create_task(self._read_loop())
        except BaseException:
            # timeout, reject, OR cancellation mid-handshake: never leave
            # the channel half-open (writer alive, no reader) — the NEXT
            # call would park for its full budget instead of failing fast
            if self.writer is not None:
                self.writer.close()
                self.writer = None
            raise

    async def _read_loop(self) -> None:
        # EVERY exit path — clean EOF (FIN), connection reset (RST: a
        # peer SIGKILLed with unread data), or any codec error — must
        # close OUR writer and fail the pending calls. An unhandled RST
        # used to kill this task silently, leaving the channel half-open:
        # `alive` still passed, the next call()'s write landed in a dead
        # socket, and its future parked for the full timeout — observed
        # as a CONNECT stalling ~35s on the clientid lock right after a
        # peer was killed (pre-nodedown-detection window).
        try:
            while True:
                msg = await read_frame(self.reader)
                if msg is None:
                    break
                if msg.get("t") == "reply":
                    fut = self._pending.pop(msg["id"], None)
                    if fut is not None and not fut.done():
                        fut.set_result(msg)
        except Exception:  # noqa: BLE001 — reset/codec: same cleanup
            pass
        finally:
            # a STALE reader cancelled by a reconnect must not touch the
            # NEW connection's state. connect() cancels the old task and
            # nulls _reader_task with NO await between the two, so by the
            # time the cancelled reader's finally runs, this guard is
            # False exactly for it (do not insert an await there)
            if self._reader_task is asyncio.current_task():
                if self.writer is not None:
                    try:
                        self.writer.close()
                    except Exception:  # noqa: BLE001
                        pass
                self._fail_pending(RpcError("connection closed"))

    def _fail_pending(self, err: Exception) -> None:
        for fut in self._pending.values():
            if not fut.done():
                fut.set_exception(err)
        self._pending.clear()

    @property
    def alive(self) -> bool:
        return self.writer is not None and not self.writer.is_closing()

    async def send(self, msg: dict) -> None:
        async with self._lock:
            if not self.alive:
                await self.connect()
            self.writer.write(encode_frame(msg))
            await self.writer.drain()

    async def call(self, fn: str, args: list,
                   timeout: float = CALL_TIMEOUT) -> Any:
        self._next_id += 1
        rid = self._next_id
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        data = encode_frame({"t": "call", "id": rid, "fn": fn, "args": args})

        async def _go():
            # register the future only once the connection is up, under
            # the send lock: connect() fails every pending future (they
            # belong to the dead connection), so registering earlier
            # would let our own reconnect kill this call
            async with self._lock:
                if not self.alive:
                    await self.connect()
                self._pending[rid] = fut
                self.writer.write(data)
                await self.writer.drain()
            return await fut

        try:
            # the timeout covers the WHOLE call — including the connect/
            # handshake phase, which parks indefinitely against a frozen
            # peer (connect() cleans up its own state on cancellation)
            reply = await asyncio.wait_for(_go(), timeout)
        except (asyncio.TimeoutError, ConnectionError, OSError) as e:
            self._pending.pop(rid, None)
            raise RpcError(f"call {fn} failed: {e}") from e
        if not reply.get("ok"):
            raise RpcError(f"remote error in {fn}: {reply.get('val')}")
        return reply.get("val")

    async def cast(self, fn: str, args: list) -> None:
        try:
            # 3.10-compatible deadline (asyncio.timeout is 3.11+;
            # utils.aio.timeout_after converts only OUR deadline
            # cancel into TimeoutError)
            async with timeout_after(CONNECT_TIMEOUT):
                await self.send({"t": "cast", "fn": fn, "args": args})
        except asyncio.TimeoutError as e:
            # a FROZEN peer stops reading: once the TCP buffers fill,
            # drain() parks forever and would wedge the (single)
            # replication worker — nodedown can't interrupt an in-flight
            # drain. The cast is doomed anyway (anti-entropy heals);
            # close the channel so later sends reconnect or fail fast.
            if self.writer is not None:
                self.writer.close()
            raise RpcError(f"cast {fn}: send timed out") from e

    async def close(self) -> None:
        if self._reader_task:
            self._reader_task.cancel()
        if self.writer:
            self.writer.close()
        self._fail_pending(RpcError("closed"))


class Peer:
    """Channel pool to one remote node; key-pinned pick
    (emqx_rpc.erl:55-57 `phash2(Key) rem tcp_client_num`)."""

    def __init__(self, host: str, port: int, self_node: str, cookie: str,
                 n_channels: int = DEFAULT_CHANNELS):
        self.addr = (host, port)
        self.channels = [_Channel(host, port, self_node, cookie)
                         for _ in range(n_channels)]

    def pick(self, key: Optional[str]) -> _Channel:
        if key is None:
            import random
            return self.channels[random.randrange(len(self.channels))]
        import zlib
        return self.channels[zlib.crc32(key.encode()) % len(self.channels)]

    async def close(self) -> None:
        for ch in self.channels:
            await ch.close()


Handler = Callable[..., Awaitable[Any]]


class RpcNode:
    """One node's RPC endpoint: TCP server + peer channel pools + the
    registered handler table (the remote-callable surface)."""

    def __init__(self, node: str, host: str = "127.0.0.1", port: int = 0,
                 cookie: str = "emqxsecretcookie",
                 n_channels: int = DEFAULT_CHANNELS):
        self.node = node
        self.host, self.port = host, port
        self.cookie = cookie
        self.n_channels = n_channels
        self.handlers: dict[str, Handler] = {}
        self.peers: dict[str, Peer] = {}        # node name -> Peer
        self._server: Optional[asyncio.AbstractServer] = None
        self._inbound: set[asyncio.StreamWriter] = set()
        self.on_inbound_connect: Optional[Callable[[str], None]] = None

    def register(self, fn: str, handler: Handler) -> None:
        self.handlers[fn] = handler

    async def start(self) -> None:
        self._server = await asyncio.start_server(
            self._serve, self.host, self.port)
        if self.port == 0:
            self.port = self._server.sockets[0].getsockname()[1]

    @property
    def address(self) -> tuple[str, int]:
        return (self.host, self.port)

    async def _serve(self, reader: asyncio.StreamReader,
                     writer: asyncio.StreamWriter) -> None:
        hello = await read_frame(reader)
        if (not hello or hello.get("t") != "hello"
                or hello.get("cookie") != self.cookie):
            writer.close()
            return
        writer.write(encode_frame({"t": "hello_ok", "node": self.node}))
        await writer.drain()
        if self.on_inbound_connect:
            self.on_inbound_connect(hello.get("node", "?"))
        self._inbound.add(writer)
        try:
            while True:
                msg = await read_frame(reader)
                if msg is None:
                    break
                t = msg.get("t")
                if t == "call":
                    spawn(self._run_call(writer, msg), "rpc-call")
                elif t == "cast":
                    spawn(self._run_cast(msg), "rpc-cast")
        finally:
            self._inbound.discard(writer)
            try:
                writer.close()
            except RuntimeError:
                # loop already closed (interpreter teardown sweeping a
                # still-parked serve coroutine) — nothing left to close
                pass

    async def _run_call(self, writer: asyncio.StreamWriter,
                        msg: dict) -> None:
        fn, args = msg.get("fn"), msg.get("args", [])
        try:
            handler = self.handlers[fn]
            val = await handler(*args)
            reply = {"t": "reply", "id": msg["id"], "ok": True, "val": val}
        except Exception as e:  # noqa: BLE001 — remote gets the error text
            log.debug("rpc call %s failed", fn, exc_info=True)
            reply = {"t": "reply", "id": msg["id"], "ok": False,
                     "val": f"{type(e).__name__}: {e}"}
        try:
            data = encode_frame(reply)
        except (TypeError, ValueError) as e:
            # a handler returned something JSON-hostile: the caller must get
            # an error, not a 10s timeout
            data = encode_frame({"t": "reply", "id": msg["id"], "ok": False,
                                 "val": f"unserializable reply: {e}"})
        try:
            writer.write(data)
            await writer.drain()
        except (ConnectionError, OSError):
            pass

    async def _run_cast(self, msg: dict) -> None:
        fn, args = msg.get("fn"), msg.get("args", [])
        handler = self.handlers.get(fn)
        if handler is None:
            return
        try:
            await handler(*args)
        except Exception:  # noqa: BLE001 — cast errors are dropped like gen_rpc
            log.debug("rpc cast %s failed", fn, exc_info=True)

    # ---- outbound ----
    def add_peer(self, node: str, host: str, port: int) -> None:
        cur = self.peers.get(node)
        if cur is not None:
            if cur.addr == (host, port):
                return
            # the node came back at a NEW address (restart with dynamic
            # ports): the old pool points at a corpse and every call
            # through it would park — replace it, closing the stale
            # channels in the background
            del self.peers[node]
            try:
                asyncio.get_running_loop()
                spawn(cur.close(), "rpc-pool-close")
            except RuntimeError:          # no loop (sync test context)
                for ch in cur.channels:
                    if ch.writer is not None:
                        ch.writer.close()
        self.peers[node] = Peer(host, port, self.node, self.cookie,
                                self.n_channels)

    async def drop_peer(self, node: str) -> None:
        peer = self.peers.pop(node, None)
        if peer:
            await peer.close()

    async def call(self, node: str, fn: str, args: list,
                   key: Optional[str] = None,
                   timeout: float = CALL_TIMEOUT) -> Any:
        """Sync call; key pins the channel (per-topic ordering)."""
        if node == self.node:
            return await self.handlers[fn](*args)
        peer = self.peers.get(node)
        if peer is None:
            raise RpcError(f"unknown peer {node}")
        return await peer.pick(key).call(fn, args, timeout)

    async def cast(self, node: str, fn: str, args: list,
                   key: Optional[str] = None) -> None:
        """Async fire-and-forget; errors dropped (gen_rpc cast)."""
        if node == self.node:
            try:
                await self.handlers[fn](*args)
            except Exception:  # noqa: BLE001
                log.debug("local cast %s failed", fn, exc_info=True)
            return
        peer = self.peers.get(node)
        if peer is None:
            return
        try:
            await peer.pick(key).cast(fn, args)
        except (RpcError, ConnectionError, OSError):
            log.debug("cast to %s failed", node, exc_info=True)

    async def multicall(self, nodes: list[str], fn: str, args: list,
                        key: Optional[str] = None) -> dict[str, Any]:
        """Parity: emqx_rpc:multicall — gather per-node results; failures
        recorded as RpcError values instead of raising."""
        async def one(n):
            try:
                return await self.call(n, fn, args, key=key)
            except RpcError as e:
                return e
        vals = await asyncio.gather(*[one(n) for n in nodes])
        return dict(zip(nodes, vals))

    async def stop(self) -> None:
        for peer in list(self.peers.values()):
            await peer.close()
        self.peers.clear()
        for w in list(self._inbound):
            w.close()
        self._inbound.clear()
        if self._server:
            self._server.close()
            try:
                await asyncio.wait_for(self._server.wait_closed(), 2)
            except asyncio.TimeoutError:
                pass
