"""Replicated metadata store: the ekka_mnesia analog.

Parity: the reference replicates routes/shared-subs/banned/etc. as mnesia
ram_copies tables with transactional writes (emqx_router.erl:77-86,
emqx_shared_sub.erl:89-97). SURVEY.md §7 re-derives this as a simpler,
stronger design: **each node is the single writer for its own entries**, and
publishes an ordered per-origin op log; every node applies every origin's log
in order, so all replicas converge without distributed transactions (the
reference's route-lock strategies emqx_router.erl:251-303 exist only because
multiple nodes mutate shared trie rows — here they never do).

Tables are bags keyed by (key, origin): an origin can only add/delete values
it owns, which makes nodedown cleanup (`purge_origin`, the emqx_router_helper
analog) exact. Late joiners get a full snapshot, then the live feed; a
per-origin sequence number discards out-of-order/duplicate casts.
"""

from __future__ import annotations

import asyncio

from emqx_tpu.broker.supervise import spawn
import logging
import time
from typing import Any, Callable, Optional

from emqx_tpu.cluster.membership import Membership
from emqx_tpu.cluster.rpc import RpcNode

log = logging.getLogger("emqx_tpu.cluster.store")


class Table:
    """One replicated bag table: key -> {origin -> [values]}."""

    def __init__(self, name: str):
        self.name = name
        self.rows: dict[Any, dict[str, list]] = {}
        self._count = 0       # live value count (count() is polled in
        #                       sync loops — O(n) scans there are O(n²))
        # fn(op, key, value, origin) on every applied mutation
        self.watchers: list[Callable[[str, Any, Any, str], None]] = []

    def _apply(self, op: str, key: Any, value: Any, origin: str) -> None:
        if op == "add":
            vals = self.rows.setdefault(key, {}).setdefault(origin, [])
            if value not in vals:
                vals.append(value)
                self._count += 1
        elif op == "del":
            per = self.rows.get(key)
            if per is None:
                return
            vals = per.get(origin)
            if vals is None:
                return
            try:
                vals.remove(value)
            except ValueError:
                return
            self._count -= 1
            if not vals:
                del per[origin]
            if not per:
                del self.rows[key]
        for w in self.watchers:
            try:
                w(op, key, value, origin)
            except Exception:  # noqa: BLE001
                log.exception("table %s watcher failed", self.name)

    # ---- reads (always local; ram_copies semantics) ----
    def lookup(self, key: Any) -> list[tuple[str, Any]]:
        """[(origin, value)] for key."""
        return [(o, v) for o, vals in self.rows.get(key, {}).items()
                for v in vals]

    def origins(self, key: Any) -> list[str]:
        return list(self.rows.get(key, {}))

    def keys(self) -> list:
        return list(self.rows)

    def count(self) -> int:
        return self._count


class ClusterStore:
    def __init__(self, rpc: RpcNode, membership: Membership):
        self.rpc = rpc
        self.membership = membership
        self.tables: dict[str, Table] = {}
        self._seq = 0                         # ops this origin has published
        # boot incarnation: a restarted origin restarts its seq at 0, and
        # a replica that kept the old origin's _applied would swallow every
        # new op as a "duplicate" (observed: a node rejoining before
        # nodedown fired was unreachable — its route ops were dropped).
        # Ops carry (incarnation, seq); a NEWER incarnation purges the
        # origin's old rows and resets its seq tracking (the analog of
        # mnesia recopying a restarted node's table). Wall-clock ns: restart
        # gaps are seconds, far above any cross-host skew that matters.
        self._inc = time.time_ns()
        self._origin_inc: dict[str, int] = {}  # origin -> its incarnation
        self._applied: dict[str, int] = {}    # origin -> last applied seq
        self._buffer: dict[str, dict[int, tuple]] = {}  # out-of-order holds
        self._lag_seen: dict[str, int] = {}   # origin -> applied at last check
        self._ae_task: Optional[asyncio.Task] = None
        rpc.register("store.op", self._h_op)
        rpc.register("store.op_batch", self._h_op_batch)
        rpc.register("store.snapshot", self._h_snapshot)
        rpc.register("store.seq", self._h_seq)
        membership.monitor(self._on_membership)

    def start_anti_entropy(self, interval_s: float = 5.0) -> None:
        """Heal replica divergence from lost casts: if an origin's applied
        seq stalls below its published seq across two checks, resync
        (mnesia would instead fall back to a full table copy on reconnect)."""
        self._ae_task = asyncio.get_running_loop().create_task(
            self._ae_loop(interval_s))

    def stop_anti_entropy(self) -> None:
        if self._ae_task:
            self._ae_task.cancel()

    async def _ae_loop(self, interval_s: float) -> None:
        from emqx_tpu.cluster.rpc import RpcError
        while True:
            await asyncio.sleep(interval_s)
            for origin in self.membership.other_nodes():
                try:
                    rseq = await self.rpc.call(origin, "store.seq", [],
                                               timeout=2)
                except RpcError:
                    continue
                applied = self._applied.get(origin, 0)
                if applied < rseq and self._lag_seen.get(origin) == applied:
                    # no progress since last check: casts were lost
                    await self._safe_sync(origin)
                self._lag_seen[origin] = self._applied.get(origin, 0)

    async def _h_seq(self) -> int:
        return self._seq

    def table(self, name: str) -> Table:
        if name not in self.tables:
            self.tables[name] = Table(name)
        return self.tables[name]

    # ---- writes: local apply + ordered broadcast ----
    async def add(self, table: str, key: Any, value: Any) -> None:
        await self._publish("add", table, key, value)

    async def delete(self, table: str, key: Any, value: Any) -> None:
        await self._publish("del", table, key, value)

    async def _publish(self, op: str, table: str, key: Any,
                       value: Any) -> None:
        me = self.rpc.node
        self._seq += 1
        self.table(table)._apply(op, key, value, me)
        for node in self.membership.other_nodes():
            # key-pinned so one origin's ops for one route key stay ordered
            await self.rpc.cast(node, "store.op",
                                [me, self._inc, self._seq, op, table, key,
                                 value],
                                key=f"{table}:{key}")

    async def add_many(self, table: str, items: list) -> None:
        """Bulk add: [(key, value)] applied locally + broadcast as ONE
        `store.op_batch` cast per peer per chunk. Bulk route churn (a
        10M-sub boot, a mass resubscribe) is RPC-frame-bound, not
        trie-bound: per-op casts cost an encode/decode round per route
        AND starve the heartbeat loop into false nodedowns (observed: a
        200k-route burst triggered repeated full resyncs). Receiver-side
        ordering needs no channel pinning — the per-origin seq buffer
        already applies ops in seq order whatever channel they rode."""
        me = self.rpc.node
        tab = self.table(table)
        batch = []
        for i, (key, value) in enumerate(items):
            self._seq += 1
            tab._apply("add", key, value, me)
            batch.append([self._seq, "add", table, key, value])
            if i % 1024 == 1023:
                # watchers do trie/index work per apply: yield so a big
                # coalesced run can't hold the loop into heartbeat misses
                await asyncio.sleep(0)
        peers = self.membership.other_nodes()
        CHUNK = 4096
        for i in range(0, len(batch), CHUNK):
            chunk = batch[i:i + CHUNK]
            for node in peers:
                await self.rpc.cast(node, "store.op_batch",
                                    [me, self._inc, chunk],
                                    key=f"{table}:batch")

    def _check_incarnation(self, origin: str, inc: int) -> bool:
        """Track the origin's boot incarnation; False = stale straggler."""
        known_inc = self._origin_inc.get(origin)
        if known_inc is None or inc > known_inc:
            # first contact, or the origin RESTARTED: its old rows are a
            # dead incarnation's state and its seq restarted at 0 — purge
            # and track the new incarnation, or every fresh op would be
            # dropped as a duplicate of the old sequence
            if known_inc is not None:
                self.purge_origin(origin)
            self._origin_inc[origin] = inc
            self._applied[origin] = 0
            self._buffer.pop(origin, None)
        elif inc < known_inc:
            return False      # straggler from a dead incarnation: drop
        return True

    def _recv_op(self, origin: str, seq: int, op: str, table: str,
                 key: Any, value: Any) -> None:
        """Seq-ordered apply with out-of-order buffering."""
        if isinstance(key, list):        # tuple keys round-trip as JSON lists
            key = tuple(key)
        last = self._applied.get(origin, 0)
        if seq <= last:
            return                          # duplicate
        buf = self._buffer.setdefault(origin, {})
        buf[seq] = (op, table, key, value)
        while last + 1 in buf:
            last += 1
            o, t, k, v = buf.pop(last)
            self.table(t)._apply(o, k, v, origin)
        self._applied[origin] = last
        # a gap means casts raced ahead on different channels; the buffered
        # ops apply the moment the missing seq arrives

    async def _h_op(self, origin: str, inc: int, seq: int, op: str,
                    table: str, key: Any, value: Any) -> None:
        if self._check_incarnation(origin, inc):
            self._recv_op(origin, seq, op, table, key, value)

    async def _h_op_batch(self, origin: str, inc: int,
                          batch: list) -> None:
        if not self._check_incarnation(origin, inc):
            return
        for i, (seq, op, table, key, value) in enumerate(batch):
            self._recv_op(origin, seq, op, table, key, value)
            if i % 1024 == 1023:
                await asyncio.sleep(0)   # see add_many: loop liveness
                if self._origin_inc.get(origin) != inc:
                    return   # origin restarted during the yield: the
                    # rest of this batch is a dead incarnation's state

    # ---- snapshot sync (mnesia copy_table analog) ----
    def _snapshot(self) -> dict:
        me = self.rpc.node
        out: dict = {"seq": self._seq, "inc": self._inc, "tables": {}}
        for name, tab in self.tables.items():
            rows = []
            for key, per in tab.rows.items():
                for v in per.get(me, []):
                    rows.append([key, v])
            out["tables"][name] = rows
        return out

    async def _h_snapshot(self) -> dict:
        return self._snapshot()

    async def sync_from(self, node: str) -> None:
        """Pull `node`'s own entries (its single-writer set) wholesale."""
        snap = await self.rpc.call(node, "store.snapshot", [])
        self.purge_origin(node)
        for name, rows in snap["tables"].items():
            tab = self.table(name)
            for key, v in rows:
                if isinstance(key, list):
                    key = tuple(key)
                tab._apply("add", key, v, node)
        self._applied[node] = snap["seq"]
        if "inc" in snap:     # a live node's snapshot is authoritative
            self._origin_inc[node] = snap["inc"]
        self._buffer.pop(node, None)

    # ---- failure cleanup (emqx_router_helper:cleanup_routes, §3.5) ----
    def purge_origin(self, origin: str) -> None:
        for tab in self.tables.values():
            for key in list(tab.rows):
                per = tab.rows[key]
                for v in per.get(origin, [])[:]:
                    tab._apply("del", key, v, origin)

    def _on_membership(self, event: str, node: str) -> None:
        if event in ("nodedown", "nodeleft"):
            self.purge_origin(node)
        elif event in ("nodeup", "healed"):
            # resync that origin's current state (it may have mutated while
            # partitioned — the autoheal path)
            try:
                asyncio.get_running_loop()
                spawn(self._safe_sync(node), "store-resync")
            except RuntimeError:
                pass   # no loop (sync test context): peer syncs on join

    async def _safe_sync(self, node: str) -> None:
        try:
            await self.sync_from(node)
        except Exception:  # noqa: BLE001
            log.info("snapshot sync from %s failed (will heal on next beat)",
                     node)
