"""Cluster layer: membership, replicated metadata, data-plane RPC.

Parity map (SURVEY.md §2.4 P4-P7, §5.8):
  - rpc.py        -> gen_rpc (key-pinned TCP channels; emqx_rpc.erl:20-60)
  - membership.py -> ekka membership/discovery (emqx_machine_schema.erl:66-111)
  - store.py      -> ekka_mnesia replicated tables (single-writer op log,
                     SURVEY.md §7 "cluster semantics without mnesia")
  - cluster.py    -> glue: route replication (emqx_router.erl ram_copies),
                     cross-node forwarding (emqx_broker.erl:262-280),
                     cluster-wide shared-sub dispatch, cm registry, locker
"""

from emqx_tpu.cluster.cluster import ClusterNode
from emqx_tpu.cluster.membership import Membership
from emqx_tpu.cluster.rpc import RpcNode
from emqx_tpu.cluster.store import ClusterStore

__all__ = ["ClusterNode", "Membership", "RpcNode", "ClusterStore"]
