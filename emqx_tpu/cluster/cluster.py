"""ClusterNode: joins one broker Node into a cluster.

Responsibilities (parity targets):
  - route replication: every node's trie holds ALL cluster filters; per-filter
    owner sets come from the replicated route table (emqx_router.erl:77-86
    ram_copies + copy_table — here ClusterStore origins)
  - cross-node PUBLISH forwarding over key-pinned channels, async cast like
    the default rpc.mode (emqx_broker.erl:262-280 forward/3)
  - cluster-wide shared-subscription dispatch: strategy pick over the
    replicated member table, directed remote delivery
    (emqx_shared_sub.erl:239-268 picks cluster-wide from mnesia)
  - cluster-wide clientid registry + session takeover/discard over rpc
    (emqx_cm_registry.erl + emqx_cm.erl:268-298 rpc takeover)
  - per-clientid distributed lock on the key's home node
    (emqx_cm_locker / ekka_locker analog)
  - nodedown route cleanup via store origin purge
    (emqx_router_helper, SURVEY.md §3.5)

Replication writes go through a single-writer queue task — the analog of the
reference's pooled router workers serializing route ops
(emqx_broker.erl:427-428, SURVEY.md P2): the broker's sync data path enqueues,
one task drains in order.
"""

from __future__ import annotations

import asyncio
import logging
import random
import zlib
from typing import Optional

from emqx_tpu.broker.message import Message
from emqx_tpu.broker.session import Session, SessionConf
from emqx_tpu.cluster.membership import Membership
from emqx_tpu.cluster.rpc import RpcError, RpcNode
from emqx_tpu.cluster.store import ClusterStore

log = logging.getLogger("emqx_tpu.cluster")

T_ROUTE = "route"        # filter -> origins (value: subscriber kind tag)
T_SHARED = "shared"      # (real, group) -> per-origin [sid, ...]
T_REGISTRY = "registry"  # clientid -> origins


def _crc(s: str) -> int:
    return zlib.crc32(s.encode())


class ClusterNode:
    def __init__(self, node, *, host: str = "127.0.0.1", port: int = 0,
                 cookie: str = "emqxsecretcookie",
                 seeds: Optional[list[tuple[str, int]]] = None,
                 heartbeat_s: float = 1.0,
                 rpc_mode: str = "async"):
        self.node = node                      # broker Node
        self.name = node.name
        self.rpc_mode = rpc_mode              # async=cast / sync=call forwards
        self.rpc = RpcNode(self.name, host, port, cookie)
        self.membership = Membership(self.rpc, heartbeat_s=heartbeat_s,
                                     seeds=seeds)
        self.store = ClusterStore(self.rpc, self.membership)
        self._repl_q: asyncio.Queue = asyncio.Queue()
        self._repl_task: Optional[asyncio.Task] = None
        self._fwd_tasks: set[asyncio.Task] = set()
        self._shared_cursors: dict[tuple[str, str], int] = {}
        self._shared_sticky: dict[tuple[str, str], tuple[str, int]] = {}
        self._lock_tab: dict[str, tuple] = {}   # clientid -> (token, deadline)
        # secondary index over T_SHARED: real topic -> live group names,
        # maintained from the table watcher (all origins) so the publish
        # hot path never scans the whole table
        self._groups_by_real: dict[str, set[str]] = {}

        self.rpc.register("broker.dispatch_fwd", self._h_dispatch_fwd)
        self.rpc.register("shared.deliver_fwd", self._h_shared_deliver)
        self.rpc.register("cm.takeover", self._h_cm_takeover)
        self.rpc.register("cm.discard", self._h_cm_discard)
        self.rpc.register("cm.kick", self._h_cm_kick)
        self.rpc.register("cm.lookup_info", self._h_cm_lookup_info)
        self.rpc.register("locker.acquire", self._h_lock_acquire)
        self.rpc.register("locker.release", self._h_lock_release)
        self.store.table(T_ROUTE).watchers.append(self._on_route_event)
        self.store.table(T_SHARED).watchers.append(self._on_shared_event)
        self.membership.monitor(self._on_membership)

    # ---- lifecycle ----
    async def start(self) -> None:
        await self.rpc.start()
        self.node.broker.cluster = self
        self.node.cm.cluster = self
        self._repl_task = asyncio.create_task(self._repl_worker())
        await self.membership.start()
        self.store.start_anti_entropy(
            max(1.0, self.membership.heartbeat_s * 5))
        # pull existing state from every seed-known peer
        for n in self.membership.other_nodes():
            try:
                await self.store.sync_from(n)
            except RpcError:
                pass
        # publish our current local state (joined with live subscriptions,
        # connected channels, parked sessions)
        broker = self.node.broker
        for real in broker.subs:
            self.local_route_add(real)
        for real, groups in broker.shared.items():
            for group, g in groups.items():
                for sid in g.members:
                    self.shared_join(real, group, sid)
        for clientid, _chan in self.node.cm.all_channels():
            self.registry_register(clientid)
        for clientid in self.node.cm._detached:
            self.registry_register(clientid)

    async def stop(self) -> None:
        dt = getattr(self, "_discovery_task", None)
        if dt is not None:      # etcd lease keepalive (discovery.py)
            dt.cancel()
            self._discovery_task = None
        md = getattr(self, "_mcast_discovery", None)
        if md is not None:      # stop answering group probes for a dead
            md.stop_responder()  # RPC address (discovery.py mcast)
            self._mcast_discovery = None
        if self._repl_task:
            try:
                await asyncio.wait_for(self._repl_q.join(), 2)
            except asyncio.TimeoutError:
                pass
            self._repl_task.cancel()
        for t in list(self._fwd_tasks):
            t.cancel()
        if self.node.broker.cluster is self:
            self.node.broker.cluster = None
        if getattr(self.node.cm, "cluster", None) is self:
            self.node.cm.cluster = None
        self.store.stop_anti_entropy()
        await self.membership.stop()
        await self.rpc.stop()

    @property
    def address(self) -> tuple[str, int]:
        return self.rpc.address

    async def join(self, host: str, port: int) -> None:
        await self.membership.join_addr(host, port)
        for n in self.membership.other_nodes():
            try:
                await self.store.sync_from(n)
            except RpcError:
                pass

    async def leave(self) -> None:
        await self.membership.leave()

    # ---- replication queue (single-writer, P2 analog) ----
    def _enqueue(self, coro_fn, *args) -> None:
        self._repl_q.put_nowait((coro_fn, args))

    async def _repl_worker(self) -> None:
        """Single-writer drain with COALESCING: a backlog of plain
        store.add ops (bulk subscribe churn) folds same-table runs into
        one add_many — one RPC frame per ~4k routes instead of one per
        route. Order is preserved: items run in queue order, and a
        non-add op flushes the pending run before it executes."""
        while True:
            items = [await self._repl_q.get()]
            while len(items) < 8192:
                try:
                    items.append(self._repl_q.get_nowait())
                except asyncio.QueueEmpty:
                    break
            run_table = None
            run: list = []

            async def flush_run():
                nonlocal run, run_table
                if run:
                    r, t = run, run_table
                    run, run_table = [], None   # clear BEFORE the await:
                    # add_many applies locally in full before casting, so
                    # a cast failure must not re-run the local applies
                    await self.store.add_many(t, r)

            async def safely(coro):
                try:
                    await coro
                except Exception:  # noqa: BLE001 — log, keep draining:
                    # local applies precede casts, so a lost cast is
                    # healed by anti-entropy; aborting the rest of the
                    # drain would lose LOCAL applies too
                    log.exception("replication op failed")

            for coro_fn, args in items:
                # NOTE == not `is`: each `self.store.add` access builds a
                # fresh bound method; `is` would never match and silently
                # disable coalescing entirely
                if coro_fn == self.store.add:
                    table, key, value = args
                    if run and table != run_table:
                        await safely(flush_run())
                    run_table = table
                    run.append((key, value))
                else:
                    await safely(flush_run())
                    await safely(coro_fn(*args))
            await safely(flush_run())
            for _ in items:
                self._repl_q.task_done()

    async def flush(self) -> None:
        """Wait until queued replication ops have been broadcast (tests)."""
        await self._repl_q.join()

    # ---- route replication (Broker callbacks; sync entry) ----
    def local_route_add(self, real: str) -> None:
        self._enqueue(self.store.add, T_ROUTE, real, "sub")

    def local_route_del(self, real: str) -> None:
        self._enqueue(self._route_del_op, real)

    async def _route_del_op(self, real: str) -> None:
        await self.store.delete(T_ROUTE, real, "sub")
        self._gc_local_route(real)

    def _gc_local_route(self, real: str) -> None:
        """Drop the filter from the local trie once NO node routes it."""
        broker = self.node.broker
        if (not self.store.table(T_ROUTE).origins(real)
                and not self._groups_by_real.get(real)
                and not broker._has_any_sub(real)):
            broker.router.delete_route(real)

    def _on_route_event(self, op: str, key, value, origin: str) -> None:
        if origin == self.rpc.node:
            return
        if op == "add":
            self.node.broker.router.add_route(key)
        else:
            self._gc_local_route(key)

    # ---- shared membership replication ----
    def shared_join(self, real: str, group: str, sid: int) -> None:
        self._enqueue(self.store.add, T_SHARED, (real, group), sid)

    def shared_leave(self, real: str, group: str, sid: int) -> None:
        self._enqueue(self._shared_leave_op, real, group, sid)

    async def _shared_leave_op(self, real: str, group: str,
                               sid: int) -> None:
        await self.store.delete(T_SHARED, (real, group), sid)
        self._gc_local_route(real)

    def _on_shared_event(self, op: str, key, value, origin: str) -> None:
        if not isinstance(key, tuple):
            return
        real, group = key
        # keep the real->groups index current for every origin (self too)
        if op == "add":
            self._groups_by_real.setdefault(real, set()).add(group)
        elif not self.store.table(T_SHARED).rows.get(key):
            groups = self._groups_by_real.get(real)
            if groups:
                groups.discard(group)
                if not groups:
                    del self._groups_by_real[real]
        if origin == self.rpc.node:
            return
        # a REMOTE membership change can flip a group between
        # locally-homed (on-device pick) and cluster-wide (host pick):
        # the device snapshot must mark the slot stale either way
        engine = getattr(self.node.broker, "device_engine", None)
        if engine is not None:
            engine.note_member_change(real, group)
        if op == "add":
            self.node.broker.router.add_route(real)
        else:
            self._gc_local_route(real)

    # ---- publish forwarding (emqx_broker:forward/3) ----
    def forward(self, msg: Message, filters: list[str]) -> int:
        """Called synchronously from Broker._route; sends one async
        forward per remote node carrying that node's matched filters."""
        tab = self.store.table(T_ROUTE)
        me = self.rpc.node
        per_node: dict[str, list[str]] = {}
        for f in filters:
            for origin in tab.origins(f):
                if origin != me and self.membership.is_running(origin):
                    per_node.setdefault(origin, []).append(f)
        if not per_node:
            return 0
        wire = msg.to_wire()
        for target, fs in per_node.items():
            self._spawn_fwd(target, "broker.dispatch_fwd",
                            [msg.topic, fs, wire], key=msg.topic)
            self.node.metrics.inc("messages.forward")
        return len(per_node)

    def _spawn_fwd(self, target: str, fn: str, args: list,
                   key: Optional[str]) -> None:
        if self.rpc_mode == "sync":
            coro = self.rpc.call(target, fn, args, key=key)
        else:
            coro = self.rpc.cast(target, fn, args, key=key)
        t = asyncio.create_task(self._guard(coro))
        self._fwd_tasks.add(t)
        t.add_done_callback(self._fwd_tasks.discard)

    @staticmethod
    async def _guard(coro) -> None:
        try:
            await coro
        except RpcError:
            pass

    async def _h_dispatch_fwd(self, topic: str, filters: list,
                              wire: dict) -> int:
        msg = Message.from_wire(wire)
        n = 0
        for f in filters:
            n += self.node.broker.dispatch(f, msg)
        return n

    # ---- cluster-wide shared dispatch ----
    def dispatch_shared(self, broker, msg: Message,
                        filters: list[str]) -> int:
        n = 0
        for real in filters:
            groups: set[str] = set(broker.shared.get(real, {}))
            groups |= self._groups_by_real.get(real, set())
            for group in groups:
                if self._dispatch_one_group(broker, real, group, msg):
                    n += 1
        return n

    def _members(self, broker, real: str, group: str) -> list[tuple[str, int]]:
        out = {(o, v) for o, v in
               self.store.table(T_SHARED).lookup((real, group))
               if self.membership.is_running(o)}
        # local members merged directly: a just-SUBACKed subscriber must be
        # eligible before the async replication queue drains
        me = self.rpc.node
        g = broker.shared.get(real, {}).get(group)
        if g:
            out |= {(me, sid) for sid in g.members}
        return sorted(out)

    def _dispatch_one_group(self, broker, real: str, group: str,
                            msg: Message) -> bool:
        members = self._members(broker, real, group)
        if not members:
            return False
        order = self._pick_order(broker, real, group, members, msg)
        me = self.rpc.node
        for origin, sid in order:
            if origin == me:
                g = broker.shared.get(real, {}).get(group)
                opts = g.members.get(sid) if g else None
                if opts is None:
                    continue
                if broker._deliver(sid, real, msg, dict(opts, share=group)):
                    if broker.shared_strategy == "sticky":
                        self._shared_sticky[(real, group)] = (origin, sid)
                    return True
                if not broker.shared_dispatch_ack:
                    return False
            else:
                # remote member: directed delivery, fire-and-forget (the
                # reference's cross-node SubPid ! send; ack protocol only
                # spans nodes when dispatch_ack is on — we treat remote
                # dispatch as accepted like rpc.mode=async forwards)
                self._spawn_fwd(origin, "shared.deliver_fwd",
                                [real, group, sid, msg.to_wire()],
                                key=msg.topic)
                if broker.shared_strategy == "sticky":
                    self._shared_sticky[(real, group)] = (origin, sid)
                return True
        return False

    def _pick_order(self, broker, real: str, group: str,
                    members: list[tuple[str, int]],
                    msg: Message) -> list[tuple[str, int]]:
        s = broker.shared_strategy
        key = (real, group)
        if s == "sticky" and self._shared_sticky.get(key) in members:
            first = self._shared_sticky[key]
        elif s == "round_robin":
            cur = self._shared_cursors.get(key, 0)
            first = members[cur % len(members)]
            self._shared_cursors[key] = (cur + 1) % len(members)
        elif s == "hash_clientid":
            first = members[_crc(msg.from_) % len(members)]
        elif s == "hash_topic":
            first = members[_crc(msg.topic) % len(members)]
        else:
            first = members[random.randrange(len(members))]
        rest = [m for m in members if m != first]
        random.shuffle(rest)
        return [first] + rest

    async def _h_shared_deliver(self, real: str, group: str, sid: int,
                                wire: dict) -> bool:
        broker = self.node.broker
        g = broker.shared.get(real, {}).get(group)
        opts = g.members.get(sid) if g else None
        if opts is None:
            return False
        return broker._deliver(sid, real, Message.from_wire(wire),
                               dict(opts, share=group))

    # ---- clientid registry + cross-node session ops (emqx_cm_registry) ----
    def registry_register(self, clientid: str) -> None:
        self._enqueue(self.store.add, T_REGISTRY, clientid, "chan")

    def registry_unregister(self, clientid: str) -> None:
        self._enqueue(self.store.delete, T_REGISTRY, clientid, "chan")

    def registry_lookup(self, clientid: str) -> list[str]:
        return [o for o in self.store.table(T_REGISTRY).origins(clientid)
                if self.membership.is_running(o)]

    # session wire maps are small; a FROZEN owner (gray failure) must
    # cost a reconnecting client one short timeout, not the 10s default —
    # an unanswered takeover means the session is lost to the corpse
    # either way (same as the owner having died)
    TAKEOVER_RPC_TIMEOUT_S = 3.0

    async def takeover_remote(self, clientid: str) -> Optional[dict]:
        """Pull a session (wire map) from whichever node owns the client."""
        me = self.rpc.node
        for origin in self.registry_lookup(clientid):
            if origin == me:
                continue
            try:
                wire = await self.rpc.call(
                    origin, "cm.takeover", [clientid], key=clientid,
                    timeout=self.TAKEOVER_RPC_TIMEOUT_S)
            except RpcError:
                continue
            if wire is not None:
                return wire
        return None

    async def discard_remote(self, clientid: str) -> None:
        me = self.rpc.node
        for origin in self.registry_lookup(clientid):
            if origin != me:
                try:
                    await self.rpc.call(
                        origin, "cm.discard", [clientid], key=clientid,
                        timeout=self.TAKEOVER_RPC_TIMEOUT_S)
                except RpcError:
                    pass

    async def _h_cm_takeover(self, clientid: str) -> Optional[dict]:
        cm = self.node.cm
        old = cm.lookup_channel(clientid)
        if old is not None:
            session = await old.takeover_begin()
            if session is None:
                return None
            pendings = await old.takeover_end()
            cm.unregister_channel(clientid, old)
            session.enqueue([(m, m.headers.get("subopts", {}))
                             for m in pendings])
            return session.to_wire()
        detached = cm._detached.pop(clientid, None)
        cm._parked_at.pop(clientid, None)
        if detached is not None:
            sid = getattr(detached, "parked_sid", None)
            if sid is not None:
                self.node.broker.subscriber_down(sid)
            self.registry_unregister(clientid)
            return detached.to_wire()
        return None

    async def _h_cm_discard(self, clientid: str) -> None:
        await self.node.cm.discard_session(clientid)

    async def _h_cm_kick(self, clientid: str) -> bool:
        return await self.node.cm.kick_session(clientid)

    async def _h_cm_lookup_info(self, clientid: str) -> Optional[dict]:
        return self.node.cm.get_channel_info(clientid)

    async def kick_session_global(self, clientid: str) -> bool:
        """Kick wherever the client lives (emqx_cm:kick_session rpc path)."""
        if await self.node.cm.kick_session(clientid):
            return True
        for origin in self.registry_lookup(clientid):
            if origin == self.rpc.node:
                continue
            try:
                if await self.rpc.call(origin, "cm.kick", [clientid],
                                       key=clientid):
                    return True
            except RpcError:
                pass
        return False

    # ---- distributed per-clientid lock (ekka_locker quorum analog) ----
    # Leased (a crashed holder frees itself at lease expiry) and taken on a
    # majority prefix of the sorted member list: nodes with transiently
    # divergent views still serialize on the first common node, and
    # sorted-order acquisition cannot deadlock.
    LOCK_LEASE_S = 15.0
    LOCK_DEADLINE_S = 30.0      # total acquire budget across all targets
    LOCK_RPC_TIMEOUT_S = 3.0    # per-call bound: a FROZEN target (gray
    # failure: TCP open, node unresponsive) must cost callers a short
    # timeout + retry until failure detection drops it from the target
    # list — not a 35s CONNECT stall (the old handler parked contended
    # acquires server-side for 30s, so calls needed a 35s timeout)

    def _lock_targets(self) -> list[str]:
        nodes = self.membership.running_nodes()   # sorted
        return nodes[:len(nodes) // 2 + 1]

    async def _h_lock_acquire(self, clientid: str, token: str,
                              lease_s: float) -> bool:
        import time
        cur = self._lock_tab.get(clientid)
        if cur is not None and cur[0] == token:
            # retry after a lost reply (or lease refresh): idempotent
            self._lock_tab[clientid] = (token, time.monotonic() + lease_s)
            return True
        # short grace for a release already in flight; sustained
        # contention reports False FAST — the caller owns retry policy
        deadline = time.monotonic() + 0.5
        while time.monotonic() < deadline:
            cur = self._lock_tab.get(clientid)
            if cur is None or cur[1] < time.monotonic():
                self._lock_tab[clientid] = (token,
                                            time.monotonic() + lease_s)
                return True
            await asyncio.sleep(0.01)
        return False

    async def _h_lock_release(self, clientid: str, token: str) -> bool:
        cur = self._lock_tab.get(clientid)
        if cur is not None and cur[0] == token:
            del self._lock_tab[clientid]
            return True
        return False

    def lock(self, clientid: str):
        """Async ctx manager: leased lock on the responsive prefix.

        Exclusion model (and its limit): each contender acquires on every
        REACHABLE target and waits out contention on any reachable-but-held
        target; unreachable targets are skipped.  Under SYMMETRIC failure
        both contenders serialize on the common responsive prefix.  Under
        ASYMMETRIC reachability (A reaches X, B does not) the two contenders
        can hold disjoint target sets and both proceed — mutual exclusion
        then rests only on the LOCK_LEASE_S lease, so the overlap window is
        bounded but nonzero.  This mirrors the availability bias of the
        reference's per-client locker (ekka_locker via emqx_cm_locker.erl):
        a takeover that double-runs is recoverable (the session migrates
        twice), whereas requiring a strict quorum would block ALL takeovers
        for a clientid whenever half the lock targets are down — the wrong
        trade for a 2-node cluster.  If stricter exclusion is ever needed,
        raise the bar here to a majority of _lock_targets().
        """
        cluster = self

        class _Guard:
            async def __aenter__(self):
                import time
                import uuid
                self.token = uuid.uuid4().hex
                self.held: list[str] = []
                deadline = time.monotonic() + cluster.LOCK_DEADLINE_S
                ok_any = False
                for target in cluster._lock_targets():
                    while True:
                        try:
                            ok = await cluster.rpc.call(
                                target, "locker.acquire",
                                [clientid, self.token,
                                 cluster.LOCK_LEASE_S],
                                key=clientid,
                                timeout=cluster.LOCK_RPC_TIMEOUT_S)
                        except RpcError:
                            # unreachable (refused) or unresponsive
                            # (frozen — the bounded call/handshake turns
                            # gray failure into this same fast error):
                            # skip the target. Mutual exclusion holds on
                            # the common RESPONSIVE prefix — both
                            # contenders still serialize on it — and the
                            # lease covers the rest. The target may have
                            # processed the acquire with the reply lost
                            # (a ~3s stall, not a death): fire a
                            # best-effort release in the BACKGROUND so an
                            # orphaned lease doesn't block this
                            # clientid's next acquire for the full lease
                            # window — awaiting it here would park this
                            # acquire on the frozen target's connect
                            # timeout, the exact stall being avoided
                            t = asyncio.get_running_loop().create_task(
                                cluster.rpc.cast(
                                    target, "locker.release",
                                    [clientid, self.token],
                                    key=clientid))
                            cluster._fwd_tasks.add(t)
                            t.add_done_callback(cluster._fwd_tasks.discard)
                            break
                        if ok:
                            self.held.append(target)
                            ok_any = True
                            break
                        # REACHABLE but contended: wait for the holder's
                        # release (or lease expiry) — never skip it, or
                        # mutual exclusion breaks
                        if time.monotonic() > deadline:
                            await self._release_held()
                            raise RpcError(
                                f"lock {clientid}: contended on {target}")
                        await asyncio.sleep(0.05)
                if not ok_any:
                    raise RpcError(f"lock {clientid}: no target reachable")
                return self

            async def _release_held(self):
                for target in self.held:
                    try:
                        await cluster.rpc.call(target, "locker.release",
                                               [clientid, self.token],
                                               key=clientid)
                    except RpcError:
                        pass   # lease expiry reclaims it
                self.held = []

            async def __aexit__(self, *exc):
                await self._release_held()
                return False
        return _Guard()

    # ---- membership events ----
    def _on_membership(self, event: str, node: str) -> None:
        # store purge already handled by ClusterStore; after a purge the
        # local trie may hold dead filters — sweep them
        broker = self.node.broker
        if event in ("nodedown", "nodeleft"):
            tab = self.store.table(T_ROUTE)
            for f in list(broker.router.topics()):
                if (not tab.origins(f)
                        and not self._groups_by_real.get(f)
                        and not broker._has_any_sub(f)):
                    broker.router.delete_route(f)
        # device snapshots bake cluster-wide shared membership in as
        # remote-ref sids: a membership transition must dirty every
        # shared slot so the next rebuild re-captures running members
        # only — otherwise device picks keep forwarding into a corpse
        # (or exclude a healed member) until unrelated churn. The host
        # path is immune (it filters by is_running at pick time).
        eng = getattr(self.node, "device_engine", None)
        if eng is not None:
            for real in set(self._groups_by_real) | set(broker.shared):
                for group in (set(self._groups_by_real.get(real, ()))
                              | set(broker.shared.get(real, ()))):
                    eng.note_member_change(real, group)

    # ---- introspection (mgmt surface) ----
    def info(self) -> dict:
        return {"node": self.rpc.node, "address": list(self.address),
                "members": self.membership.info(),
                "routes": self.store.table(T_ROUTE).count(),
                "shared": self.store.table(T_SHARED).count(),
                "registry": self.store.table(T_REGISTRY).count()}
