"""Cluster discovery strategies + autocluster.

Parity: ekka autocluster as configured by emqx_machine
(/root/reference/apps/emqx_machine/src/emqx_machine_schema.erl:66-111 —
strategies manual | static | mcast | dns | etcd | k8s, plus
cluster_autoheal/cluster_autoclean which live in
emqx_tpu/cluster/membership.py). Each strategy resolves to a list of
(host, port) seed addresses; `autocluster` joins the local ClusterNode to
every discovered peer, and registry/announce strategies (etcd, mcast)
publish the local node first so cold-started clusters can find each
other.
"""

from __future__ import annotations

import asyncio
import base64
import json
import logging
from typing import Callable, Optional

log = logging.getLogger("emqx_tpu.discovery")


class Discovery:
    """Behaviour: discover() -> list of (host, port) seeds."""

    strategy = "manual"

    async def discover(self) -> list[tuple[str, int]]:
        return []


class ManualDiscovery(Discovery):
    """No automatic discovery; nodes join via explicit `join` (the
    reference's default)."""

    strategy = "manual"


class StaticDiscovery(Discovery):
    """Fixed seed list: ["host:port", ...] or [(host, port), ...]."""

    strategy = "static"

    def __init__(self, seeds: list):
        self._seeds = []
        for s in seeds:
            if isinstance(s, str):
                host, sep, port = s.rpartition(":")
                if not sep or not host or not port.isdigit():
                    raise ValueError(
                        f"static discovery seed {s!r} must be "
                        f"\"host:port\" (IPv6: \"[addr]:port\")")
                self._seeds.append((host.strip("[]"), int(port)))
            else:
                self._seeds.append((s[0], int(s[1])))

    async def discover(self) -> list[tuple[str, int]]:
        return list(self._seeds)


class DnsDiscovery(Discovery):
    """A-record discovery: every address behind `name` is a peer on
    `port` (emqx_machine_schema dns strategy: name + app)."""

    strategy = "dns"

    def __init__(self, name: str, port: int,
                 resolver: Optional[Callable] = None):
        self.name = name
        self.port = port
        self._resolver = resolver      # injectable for tests

    async def discover(self) -> list[tuple[str, int]]:
        if self._resolver is not None:
            addrs = self._resolver(self.name)
            if asyncio.iscoroutine(addrs):
                addrs = await addrs
        else:
            try:
                infos = await asyncio.get_running_loop().getaddrinfo(
                    self.name, self.port)
            except OSError as e:
                log.warning("dns discovery for %s failed: %s",
                            self.name, e)
                return []
            addrs = sorted({i[4][0] for i in infos})
        return [(a, self.port) for a in addrs]


class EtcdDiscovery(Discovery):
    """etcd v3 kv range over the HTTP/JSON gateway: peers register
    themselves under `<prefix>/<cluster>/nodes/<name>` with value
    "host:port" (the ekka etcd strategy's key scheme)."""

    strategy = "etcd"

    def __init__(self, server: str, prefix: str = "emqxcl",
                 cluster_name: str = "emqx_tpu", timeout: float = 5.0):
        self.server = server.rstrip("/")
        self.prefix = prefix
        self.cluster_name = cluster_name
        self.timeout = timeout

    def _range_key(self) -> tuple[str, str]:
        key = f"{self.prefix}/{self.cluster_name}/nodes/"
        end = key[:-1] + chr(ord(key[-1]) + 1)
        return key, end

    async def discover(self) -> list[tuple[str, int]]:
        from emqx_tpu.utils.http import request
        key, end = self._range_key()
        body = json.dumps({
            "key": base64.b64encode(key.encode()).decode(),
            "range_end": base64.b64encode(end.encode()).decode(),
        }).encode()
        try:
            resp = await request(
                "POST", self.server + "/v3/kv/range", body=body,
                headers={"content-type": "application/json"},
                timeout=self.timeout)
            kvs = resp.json().get("kvs", [])
        except Exception as e:  # noqa: BLE001
            log.warning("etcd discovery failed: %s", e)
            return []
        out = []
        for kv in kvs:
            val = base64.b64decode(kv.get("value", "")).decode()
            host, _, port = val.rpartition(":")
            if host and port.isdigit():
                out.append((host, int(port)))
        return out

    async def register(self, host: str, port: int, node_name: str,
                       ttl: int = 60) -> Optional[str]:
        """Publish the local node under the discovery prefix, bound to a
        TTL lease so a crashed node's address expires (the ekka etcd
        strategy's node_ttl). Returns the lease id for keepalive."""
        from emqx_tpu.utils.http import request
        lease_id = None
        try:
            resp = await request(
                "POST", self.server + "/v3/lease/grant",
                body=json.dumps({"TTL": ttl}).encode(),
                headers={"content-type": "application/json"},
                timeout=self.timeout)
            lease_id = resp.json().get("ID")
        except Exception as e:  # noqa: BLE001 (older gateway: no lease)
            log.warning("etcd lease grant failed (registering without "
                        "TTL): %s", e)
        key = f"{self.prefix}/{self.cluster_name}/nodes/{node_name}"
        body = {"key": base64.b64encode(key.encode()).decode(),
                "value": base64.b64encode(
                    f"{host}:{port}".encode()).decode()}
        if lease_id is not None:
            body["lease"] = lease_id
        try:
            await request("POST", self.server + "/v3/kv/put",
                          body=json.dumps(body).encode(),
                          headers={"content-type": "application/json"},
                          timeout=self.timeout)
        except Exception as e:  # noqa: BLE001 — degrade like discover()
            log.warning("etcd registration failed (node stays "
                        "unregistered): %s", e)
            return None
        return lease_id

    async def keepalive_loop(self, lease_id: str, ttl: int = 60) -> None:
        """Refresh the registration lease every ttl/3 seconds."""
        from emqx_tpu.utils.http import request
        while True:
            await asyncio.sleep(max(1, ttl // 3))
            try:
                await request(
                    "POST", self.server + "/v3/lease/keepalive",
                    body=json.dumps({"ID": lease_id}).encode(),
                    headers={"content-type": "application/json"},
                    timeout=self.timeout)
            except Exception as e:  # noqa: BLE001
                log.warning("etcd lease keepalive failed: %s", e)


class K8sDiscovery(Discovery):
    """Kubernetes endpoints discovery: every ready address of
    `service_name` in `namespace` is a peer (emqx_machine_schema k8s
    strategy: apiserver + service_name + namespace + address_type)."""

    strategy = "k8s"

    def __init__(self, apiserver: str, service_name: str,
                 namespace: str = "default", port: int = 4370,
                 token: Optional[str] = None, timeout: float = 5.0):
        self.apiserver = apiserver.rstrip("/")
        self.service_name = service_name
        self.namespace = namespace
        self.port = port
        self.token = token
        self.timeout = timeout

    async def discover(self) -> list[tuple[str, int]]:
        from emqx_tpu.utils.http import request
        url = (f"{self.apiserver}/api/v1/namespaces/{self.namespace}"
               f"/endpoints/{self.service_name}")
        headers = {}
        if self.token:
            headers["authorization"] = f"Bearer {self.token}"
        try:
            resp = await request("GET", url, headers=headers,
                                 timeout=self.timeout)
            doc = resp.json()
        except Exception as e:  # noqa: BLE001
            log.warning("k8s discovery failed: %s", e)
            return []
        out = []
        for subset in doc.get("subsets", []):
            port = self.port
            for p in subset.get("ports", []):
                if p.get("name") in (None, "ekka", "cluster"):
                    port = p.get("port", port)
            for addr in subset.get("addresses", []):
                ip = addr.get("ip")
                if ip:
                    out.append((ip, port))
        return out


class McastDiscovery(Discovery):
    """UDP multicast probe/response (the ekka mcast strategy: addr +
    ports + ttl + loop + iface, emqx_machine_schema cluster.mcast block).
    Every node runs responders joined to the group on each configured
    port; discover() multicasts a probe to every port and collects
    unicast replies for `wait_s`. The reply carries the peer's
    advertised RPC address, so the probe socket needs no group
    membership of its own."""

    strategy = "mcast"
    _MAGIC = b"EMQXTPU-MCAST1"

    def __init__(self, addr: str = "239.192.0.1", port=45369,
                 cluster_name: str = "emqx_tpu", ttl: int = 1,
                 loop_enable: bool = True, iface: str = "0.0.0.0",
                 wait_s: float = 1.0):
        self.addr = addr
        self.ports = [int(p) for p in
                      (port if isinstance(port, (list, tuple)) else [port])]
        if not self.ports:
            raise ValueError("mcast discovery needs at least one port")
        self.port = self.ports[0]
        self.cluster_name = cluster_name
        self.ttl = ttl
        self.loop_enable = loop_enable
        self.iface = iface
        self.wait_s = wait_s
        self._responders: list[asyncio.DatagramTransport] = []

    # one definition of the wire format — an exact-match compare on the
    # responder side means any drift between builder copies silently
    # breaks discovery
    def _probe(self) -> bytes:
        return self._MAGIC + b" PROBE " + self.cluster_name.encode()

    def _reply_prefix(self) -> bytes:
        return self._MAGIC + b" NODE " + self.cluster_name.encode() + b" "

    def _mcast_opts(self, s) -> None:
        import socket
        s.setsockopt(socket.IPPROTO_IP, socket.IP_MULTICAST_TTL, self.ttl)
        s.setsockopt(socket.IPPROTO_IP, socket.IP_MULTICAST_LOOP,
                     1 if self.loop_enable else 0)
        if self.iface != "0.0.0.0":
            # multihomed host: transmit on the configured interface, not
            # the default route
            s.setsockopt(socket.IPPROTO_IP, socket.IP_MULTICAST_IF,
                         socket.inet_aton(self.iface))

    def _group_sock(self, bind_port: int):
        import socket
        import struct
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        try:
            s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            if hasattr(socket, "SO_REUSEPORT"):  # several nodes per host
                s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEPORT, 1)
            s.bind(("", bind_port))
            mreq = struct.pack("4s4s", socket.inet_aton(self.addr),
                               socket.inet_aton(self.iface))
            s.setsockopt(socket.IPPROTO_IP, socket.IP_ADD_MEMBERSHIP, mreq)
            self._mcast_opts(s)
            s.setblocking(False)
            return s
        except OSError:
            s.close()
            raise

    async def start_responder(self, host: str, port: int) -> None:
        """Join the group on every configured port and answer probes for
        our cluster with the advertised RPC address. Idempotent."""
        if self._responders:
            return
        probe = self._probe()
        reply = self._reply_prefix() + f"{host}:{port}".encode()

        class _Responder(asyncio.DatagramProtocol):
            def connection_made(self, transport):
                self.transport = transport

            def datagram_received(self, data, addr):
                if data == probe:
                    self.transport.sendto(reply, addr)

        loop = asyncio.get_running_loop()
        for bind_port in self.ports:
            transport, _ = await loop.create_datagram_endpoint(
                _Responder, sock=self._group_sock(bind_port))
            self._responders.append(transport)

    def stop_responder(self) -> None:
        for t in self._responders:
            t.close()
        self._responders = []

    async def discover(self) -> list[tuple[str, int]]:
        import socket
        loop = asyncio.get_running_loop()
        found: set[tuple[str, int]] = set()
        want = self._reply_prefix()

        class _Collector(asyncio.DatagramProtocol):
            def datagram_received(self, data, addr):
                if not data.startswith(want):
                    return
                hp = data[len(want):].decode(errors="replace")
                h, _, p = hp.rpartition(":")
                if h and p.isdigit():
                    found.add((h, int(p)))

            def error_received(self, exc):
                # asyncio routes sendto OSErrors here, not to the caller
                # (e.g. ENETUNREACH: no multicast route)
                log.warning("mcast discovery failed: %s", exc)

        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        try:
            self._mcast_opts(s)
            s.setblocking(False)
        except OSError:
            s.close()
            raise
        transport, _ = await loop.create_datagram_endpoint(
            _Collector, sock=s)
        try:
            for p in self.ports:
                transport.sendto(self._probe(), (self.addr, p))
            await asyncio.sleep(self.wait_s)
        finally:
            transport.close()
        return sorted(found)


def from_config(conf: dict,
                resolver: Optional[Callable] = None) -> Discovery:
    """Build the configured strategy from the `cluster` config section
    (emqx_machine_schema cluster.discovery + per-strategy blocks)."""
    strategy = (conf or {}).get("discovery", "manual")
    if strategy == "manual":
        return ManualDiscovery()
    if strategy == "static":
        return StaticDiscovery(conf.get("nodes") or conf.get("seeds") or [])
    if strategy == "dns":
        dconf = conf.get("dns") or {}
        return DnsDiscovery(dconf.get("name", conf.get("name", "")),
                            int(dconf.get("port", 4370)),
                            resolver=resolver)
    if strategy == "etcd":
        econf = conf.get("etcd") or {}
        return EtcdDiscovery(econf.get("server", "http://127.0.0.1:2379"),
                             econf.get("prefix", "emqxcl"),
                             conf.get("name", "emqx_tpu"))
    if strategy == "mcast":
        mconf = conf.get("mcast") or {}
        ports = mconf.get("ports", 45369)
        if isinstance(ports, list) and not ports:
            raise ValueError("cluster.mcast.ports must not be empty")
        return McastDiscovery(
            addr=mconf.get("addr", "239.192.0.1"),
            port=ports,
            cluster_name=conf.get("name", "emqx_tpu"),
            ttl=int(mconf.get("ttl", 1)),
            loop_enable=bool(mconf.get("loop", True)),
            iface=mconf.get("iface", "0.0.0.0"))
    if strategy == "k8s":
        kconf = conf.get("k8s") or {}
        return K8sDiscovery(
            kconf.get("apiserver", "http://127.0.0.1:8080"),
            kconf.get("service_name", "emqx"),
            kconf.get("namespace", "default"),
            int(kconf.get("port", 4370)), kconf.get("token"))
    raise ValueError(f"unknown discovery strategy {strategy!r}")


async def autocluster(cluster_node, discovery: Optional[Discovery] = None,
                      resolver: Optional[Callable] = None) -> int:
    """Resolve seeds via the configured strategy and join each
    (emqx_machine_app start_autocluster). Returns the number of peers
    joined."""
    if discovery is None:
        discovery = from_config(
            cluster_node.node.config.get("cluster") or {},
            resolver=resolver)
    me = cluster_node.address
    if isinstance(discovery, McastDiscovery):
        # announce-style strategy: answer the group's probes from now on;
        # ClusterNode.stop() closes the responder via this handle
        await discovery.start_responder(me[0], me[1])
        cluster_node._mcast_discovery = discovery
    if isinstance(discovery, EtcdDiscovery):
        # registry-style strategies need the local node published BEFORE
        # discovering, or a cold-started cluster finds nobody
        lease = await discovery.register(me[0], me[1],
                                         cluster_node.name)
        if lease is not None:
            task = asyncio.ensure_future(discovery.keepalive_loop(lease))
            prev = getattr(cluster_node, "_discovery_task", None)
            if prev is not None:
                prev.cancel()
            cluster_node._discovery_task = task
    seeds = await discovery.discover()
    joined = 0
    for host, port in seeds:
        if (host, port) == me:
            continue
        try:
            await cluster_node.join(host, port)
            joined += 1
        except Exception as e:  # noqa: BLE001
            log.warning("autocluster join %s:%d failed: %s", host, port, e)
    return joined
