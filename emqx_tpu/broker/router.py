"""Host route table: topic-filter routes with device-accelerated matching.

Parity: emqx_router.erl (route add/delete + match_routes, :113-141) and
emqx_trie.erl (wildcard-filter trie). Architecture differs by design
(SURVEY.md §7): routes live host-side in an authoritative `HostTrie` +
exact-match dict (the reference also short-circuits exact topics past the
trie, emqx_router.erl:136-141), while wildcard matching for publish
micro-batches runs on TPU against a compiled columnar `TrieTables` snapshot.

Snapshot protocol (the "mutable trie on immutable arrays" answer):
  - every wildcard route add/delete updates `HostTrie` immediately and is
    also recorded in a delta trie (adds) relative to the last device build;
  - device match = device fids (validated against the *current* route set,
    which subsumes deletions) ∪ delta-trie matches ∪ exact lookups;
  - when the delta exceeds `rebuild_threshold`, the columnar tables are
    rebuilt (double-buffered: the old snapshot serves until the swap).

Single-writer: all mutations must come from one task, the analog of the
reference's pooled router workers serializing route ops
(emqx_broker.erl:427-428).
"""

from __future__ import annotations

import threading
from typing import Optional

import numpy as np

from emqx_tpu.ops import intern as I
from emqx_tpu.ops.trie import HostTrie, TrieTables, build_tables
from emqx_tpu.utils import topic as T


class Router:
    def __init__(self, *, use_device: bool = True,
                 rebuild_threshold: int = 256,
                 max_levels: int = 16,
                 frontier_cap: int = 16, match_cap: int = 64,
                 device_min_batch: int = 4):
        self.intern = I.InternTable()
        self.use_device = use_device
        self.rebuild_threshold = rebuild_threshold
        self.max_levels = max_levels
        self.frontier_cap = frontier_cap
        self.match_cap = match_cap
        self.device_min_batch = device_min_batch

        # authoritative state
        self.exact: set[str] = set()                # non-wildcard routed topics
        self.wildcards: dict[str, int] = {}         # filter -> fid
        self._fid_words: dict[int, list[int]] = {}  # fid -> interned words
        self._fid_filter: dict[int, str] = {}       # fid -> filter string
        self._next_fid = 0
        self.host_trie = HostTrie()

        # filter-universe change listener (DeviceRouteEngine): called with
        # (topic_filter, added: bool) after every successful mutation
        self.on_route_change = None

        # device snapshot
        self._tables: Optional[TrieTables] = None
        self._built_row_to_filter: list[str] = []   # device row idx -> filter
        self._delta_trie = HostTrie()               # adds since last build
        self._delta_fids: dict[int, str] = {}       # fid in delta -> filter
        self._delta_count = 0                       # adds + deletes since build
        self._match_batch_fn = None

    # ---- route table mutation (emqx_router:do_add_route/do_delete_route) ----
    def add_route(self, topic_filter: str) -> bool:
        """Install a route; returns True if new. Idempotent."""
        if not T.wildcard(topic_filter):
            if topic_filter in self.exact:
                return False
            self.exact.add(topic_filter)
            if self.on_route_change:
                self.on_route_change(topic_filter, True)
            return True
        if topic_filter in self.wildcards:
            return False
        words = self.intern.encode_filter(T.tokens(topic_filter))
        fid = self._next_fid
        self._next_fid += 1
        self.wildcards[topic_filter] = fid
        self._fid_words[fid] = words
        self._fid_filter[fid] = topic_filter
        self.host_trie.insert(words, fid)
        self._delta_trie.insert(words, fid)
        self._delta_fids[fid] = topic_filter
        self._delta_count += 1
        if self.on_route_change:
            self.on_route_change(topic_filter, True)
        return True

    def delete_route(self, topic_filter: str) -> bool:
        if not T.wildcard(topic_filter):
            if topic_filter not in self.exact:
                return False
            self.exact.discard(topic_filter)
            if self.on_route_change:
                self.on_route_change(topic_filter, False)
            return True
        fid = self.wildcards.pop(topic_filter, None)
        if fid is None:
            return False
        words = self._fid_words.pop(fid)
        self._fid_filter.pop(fid, None)
        self.host_trie.delete(words)
        if fid in self._delta_fids:
            self._delta_trie.delete(words)
            del self._delta_fids[fid]
        self._delta_count += 1
        if self.on_route_change:
            self.on_route_change(topic_filter, False)
        return True

    def has_route(self, topic_filter: str) -> bool:
        return topic_filter in self.exact or topic_filter in self.wildcards

    def topics(self) -> list[str]:
        """Parity: emqx_router:topics/0."""
        return sorted(self.exact) + sorted(self.wildcards)

    def route_count(self) -> int:
        return len(self.exact) + len(self.wildcards)

    # ---- matching ----
    def match(self, topic: str) -> list[str]:
        """All routed filters matching one publish topic
        (emqx_router:match_routes/1). Host path — always authoritative."""
        words = T.tokens(topic)
        out = [topic] if topic in self.exact else []
        ids = self.intern.encode_topic(words)
        dollar = words[0].startswith("$") if words else False
        for fid in self.host_trie.match(ids, dollar):
            f = self._fid_filter.get(fid)
            if f is not None:
                out.append(f)
        return out

    def match_batch(self, topics: list[str]) -> list[list[str]]:
        """Match a micro-batch; device-accelerated when profitable."""
        if (not self.use_device or len(topics) < self.device_min_batch
                or not self.wildcards):
            return [self.match(t) for t in topics]
        self._maybe_rebuild()
        if self._tables is None:
            return [self.match(t) for t in topics]
        return self._match_batch_device(topics)

    def _maybe_rebuild(self, force: bool = False) -> None:
        if self._tables is not None and not force and \
                self._delta_count < self.rebuild_threshold:
            return
        self.rebuild()

    def rebuild(self) -> None:
        """Compile the current wildcard set into fresh device tables."""
        n = len(self.wildcards)
        if n == 0:
            self._tables = None
            self._built_row_to_filter = []
        else:
            filters = list(self.wildcards.items())  # (filter, fid)
            L = max(self.max_levels,
                    max(len(self._fid_words[fid]) for _, fid in filters))
            rows = np.zeros((n, L), np.int32)
            lens = np.zeros(n, np.int64)
            for i, (_f, fid) in enumerate(filters):
                w = self._fid_words[fid]
                rows[i, :len(w)] = w
                lens[i] = len(w)
            node_cap = max(256, 2 * (int(lens.sum()) + 1))
            self._tables = build_tables(rows, lens, node_capacity=node_cap,
                                        slot_capacity=max(256, 4 * node_cap))
            self._built_row_to_filter = [f for f, _fid in filters]
        self._delta_trie = HostTrie()
        self._delta_fids = {}
        self._delta_count = 0

    def _match_batch_device(self, topics: list[str]) -> list[list[str]]:
        from emqx_tpu.ops.match import encode_topics, match_batch
        words_list = [T.tokens(t) for t in topics]
        # topics deeper than the built level budget fall back host-side
        deep = {i for i, w in enumerate(words_list) if len(w) > self.max_levels}
        enc, lens, dollar, _ = encode_topics(
            self.intern,
            [w[:self.max_levels] for w in words_list], self.max_levels)
        mr = match_batch(self._tables, enc, lens, dollar,
                         frontier_cap=self.frontier_cap,
                         match_cap=self.match_cap)
        matches = np.asarray(mr.matches)
        counts = np.asarray(mr.counts)
        overflow = np.asarray(mr.overflow)
        out: list[list[str]] = []
        for i, t in enumerate(topics):
            if i in deep or overflow[i]:
                out.append(self.match(t))
                continue
            res = [t] if t in self.exact else []
            seen = set()
            for fid in matches[i][:counts[i]]:
                if fid < 0:
                    continue
                f = self._built_row_to_filter[fid]
                # deletion since build → filter no longer active
                if f in self.wildcards and f not in seen:
                    seen.add(f)
                    res.append(f)
            ids = self.intern.encode_topic(words_list[i])
            dol = words_list[i][0].startswith("$") if words_list[i] else False
            for fid in self._delta_trie.match(ids, dol):
                f = self._delta_fids.get(fid)
                if f is not None and f not in seen:
                    seen.add(f)
                    res.append(f)
            out.append(res)
        return out

    def stats(self) -> dict:
        return {"routes": self.route_count(),
                "wildcard_routes": len(self.wildcards),
                "exact_routes": len(self.exact),
                "delta_since_build": self._delta_count,
                "built_filters": len(self._built_row_to_filter)}
