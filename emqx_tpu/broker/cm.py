"""Connection/session manager: clientid → channel registry + session lifecycle.

Parity: emqx_cm.erl — register/unregister channel, open_session with
clean-start discard or takeover-resume (emqx_cm.erl:208-298), per-clientid
locking (emqx_cm_locker), kick/discard. The reference's 2-phase
`{takeover,'begin'/'end'}` call to the old connection becomes two async
callbacks on the old channel object.
"""

from __future__ import annotations

import asyncio
from typing import Any, Optional, Protocol

from emqx_tpu.broker.session import Session, SessionConf


class ChannelLike(Protocol):
    async def takeover_begin(self) -> Optional[Session]: ...
    async def takeover_end(self) -> list: ...
    async def kick(self, reason: str) -> None: ...


class ConnectionManager:
    def __init__(self):
        self._channels: dict[str, Any] = {}     # clientid -> channel
        self._info: dict[str, dict] = {}        # clientid -> conn info map
        self._locks: dict[str, asyncio.Lock] = {}
        # detached persistent sessions (expiry > 0, connection gone)
        self._detached: dict[str, Session] = {}
        self._parked_at: dict[str, float] = {}
        self.broker = None      # wired by Node for parked-session cleanup
        self.cluster = None     # wired by ClusterNode (registry + takeover)
        self.max_count = 0

    # ---- registry (emqx_cm:register_channel/3 :124-131) ----
    def register_channel(self, clientid: str, channel: Any,
                         info: Optional[dict] = None) -> None:
        self._channels[clientid] = channel
        self._info[clientid] = info or {}
        self.max_count = max(self.max_count, len(self._channels))
        if self.cluster:
            self.cluster.registry_register(clientid)

    def unregister_channel(self, clientid: str, channel: Any = None) -> None:
        if channel is None or self._channels.get(clientid) is channel:
            self._channels.pop(clientid, None)
            self._info.pop(clientid, None)
            if self.cluster:
                self.cluster.registry_unregister(clientid)

    def lookup_channel(self, clientid: str) -> Optional[Any]:
        return self._channels.get(clientid)

    def set_channel_info(self, clientid: str, info: dict) -> None:
        if clientid in self._channels:
            self._info[clientid] = info

    def get_channel_info(self, clientid: str) -> Optional[dict]:
        return self._info.get(clientid)

    def all_channels(self) -> list[tuple[str, Any]]:
        return list(self._channels.items())

    def count(self) -> int:
        return len(self._channels)

    def _lock(self, clientid: str) -> asyncio.Lock:
        return self._locks.setdefault(clientid, asyncio.Lock())

    # ---- session lifecycle (emqx_cm:open_session/3 :208-240) ----
    async def open_session(self, clean_start: bool, clientid: str,
                           conf: SessionConf,
                           new_channel: Any) -> tuple[Session, bool]:
        """Returns (session, session_present). Serialized per clientid
        (the emqx_cm_locker analog)."""
        lock = (self.cluster.lock(clientid) if self.cluster
                else self._lock(clientid))
        async with lock:
            if clean_start:
                await self.discard_session(clientid)
                if self.cluster:
                    await self.cluster.discard_remote(clientid)
                return Session(clientid, conf), False
            # try takeover from a live channel first
            old = self._channels.get(clientid)
            if old is not None and old is not new_channel:
                session = await old.takeover_begin()
                if session is not None:
                    pendings = await old.takeover_end()
                    self.unregister_channel(clientid, old)
                    session.conf = conf
                    # pendings are raw routed messages buffered during the
                    # takeover window — run them through the session's
                    # subopts enrichment (QoS cap, nl, rap) like any other
                    # delivery (emqx_channel.erl:754-759)
                    session.enqueue([(m, m.headers.get("subopts", {}))
                                     for m in pendings])
                    return session, True
            detached = self._detached.pop(clientid, None)
            self._parked_at.pop(clientid, None)
            if detached is not None:
                detached.conf = conf
                return detached, True
            if self.cluster:
                # the client may live on another node (emqx_cm:268-298
                # rpc takeover via the cm registry)
                wire = await self.cluster.takeover_remote(clientid)
                if wire is not None:
                    session = Session.from_wire(wire, conf)
                    return session, True
            return Session(clientid, conf), False

    async def discard_session(self, clientid: str) -> None:
        """Kick any existing channel and drop its session
        (emqx_cm:discard_session). Goes through unregister_channel so the
        cluster registry entry is retired with the channel."""
        old = self._channels.get(clientid)
        self.unregister_channel(clientid)
        self.drop_parked(clientid)
        if old is not None:
            try:
                await old.kick("discarded")
            except Exception:  # noqa: BLE001 — the old channel may be
                pass           # half-dead already; the takeover wins

    async def kick_session(self, clientid: str) -> bool:
        """Administrative kick (emqx_cm:kick_session)."""
        old = self._channels.get(clientid)
        if old is None:
            return False
        self.unregister_channel(clientid)
        try:
            await old.kick("kicked")
        except Exception:  # noqa: BLE001 — a dying channel must not
            pass           # fail the administrative kick
        return True

    # ---- persistent-session parking ----
    def park_session(self, clientid: str, session: Session) -> None:
        """Hold a session whose connection closed with expiry > 0; its
        broker subscriptions stay live (sid re-pointed by the channel) so
        offline messages keep enqueueing. The clientid stays in the cluster
        registry so a reconnect on another node can find and take it over
        (emqx_cm_registry keeps entries for disconnected persistent
        sessions too)."""
        import time
        self._detached[clientid] = session
        self._parked_at[clientid] = time.monotonic()
        if self.cluster:
            self.cluster.registry_register(clientid)

    def drop_parked(self, clientid: str) -> None:
        sess = self._detached.pop(clientid, None)
        self._parked_at.pop(clientid, None)
        if sess is not None:
            if self.broker is not None:
                sid = getattr(sess, "parked_sid", None)
                if sid is not None:
                    self.broker.subscriber_down(sid)
            if self.cluster:
                self.cluster.registry_unregister(clientid)

    def sweep_expired_sessions(self) -> int:
        """Expire parked sessions past their session_expiry_interval
        (the reference's session-expiry timer)."""
        import time
        now = time.monotonic()
        gone = [cid for cid, sess in self._detached.items()
                if now - self._parked_at.get(cid, now)
                > sess.conf.session_expiry_interval]
        for cid in gone:
            self.drop_parked(cid)
        return len(gone)

    def parked_count(self) -> int:
        return len(self._detached)

    def stats_fun(self, stats) -> None:
        stats.setstat("connections.count", len(self._channels),
                      "connections.max")
        stats.setstat("sessions.count",
                      len(self._channels) + len(self._detached),
                      "sessions.max")
