"""HBM ledger: per-category accounting of persistent device allocations.

The 10M-subscription north star is ultimately an HBM-budget question,
yet until ISSUE 8 nothing accounted for device memory: snapshot tables,
delta-overlay versions and mesh shard tables were all `jax.device_put`
into the void. This module is the third leg of the observability stack
— PR 1 instrumented time (stage histograms), PR 7 causality (flight-
recorder spans), this instruments **space**:

- **`ledger.hold(category, pytree, owner=...)`** — the thin wrapper
  every persistent `device_put` site routes through
  (`broker/device_engine.py`, `parallel/serving.py`,
  `parallel/sharded.py`). It walks the pytree, sums leaf `.nbytes`
  into the category's live-bytes gauge (+ peak watermark), attaches a
  `weakref.finalize` per leaf and returns the pytree unchanged —
  release is AUTOMATIC when the arrays are garbage-collected (a
  snapshot swap dropping the old tables shows up as a release without
  any explicit call), so the ledger can never wedge a swap. Leaves are
  deduplicated by identity: holding an aliased array twice counts it
  once.
- **Pin sentinel** — dispatch handles pin the snapshot they ran
  against (the engine defers swaps while any handle is outstanding);
  a leaked handle therefore silently blocks swaps AND holds the old
  snapshot's HBM. `pin()`/`unpin()` track each in-flight handle
  against the window clock (`note_window()`), and a pin older than
  `broker.pin_warn_windows` / `EMQX_TPU_PIN_WARN_WINDOWS` windows
  fires once: the `pipeline.memory.pin_warnings` counter, the
  `pipeline.pin_stale` hook (apps/tracer logs it at WARNING) and a
  `stale_pin` instant event on the flight recorder's timeline (the
  same surface supervise trips land on).
- **`section()`** — the `memory` section of
  `PipelineTelemetry.snapshot()`, published by all four exporters
  ($SYS `pipeline/memory`, Prometheus/StatsD via the Stats gauges
  below, REST `GET /api/v5/pipeline/memory`): per-category live
  bytes / peak watermarks / hold counts / owners, pin ages in
  windows, and — where the backend exposes it —
  `jax.local_devices()[0].memory_stats()` (bytes_in_use) as the
  cross-check (`accounted_fraction` = ledger live / bytes_in_use; a
  fraction well below 1 under load means allocations are bypassing
  the ledger — the gate `tools/check_hbm_hygiene.py` catches the
  static cases).

Counters in the shared Metrics registry (every exporter carries them):
`pipeline.memory.holds` / `pipeline.memory.releases` (+ the byte
totals `pipeline.memory.hold_bytes` / `pipeline.memory.release_bytes`)
and `pipeline.memory.pin_warnings`. The count pair is symmetric BY
LEAF — `holds` counts newly-accounted array leaves exactly as
`releases` counts leaf finalizer fires, so `holds - releases` is the
live leaf count and the pair reconciles to zero at quiescence (the
byte pair reconciles the same way). The per-category `holds` row in
`section()` is a different number on purpose: it counts `hold()`
registration calls (a cursor re-adopt that added no new leaves still
counts), i.e. allocation *activity*, not leaf population. Point-in-time gauges ride the
Stats table via `stats_fun` (Prometheus gauge family, StatsD `|g`,
`$SYS .../stats/`): `pipeline.memory.live_bytes`,
`pipeline.memory.peak_bytes`, `pipeline.memory.pinned_handles`,
`pipeline.memory.max_pin_age_windows`.

Knob: `broker.hbm_ledger` / `EMQX_TPU_HBM_LEDGER` (config beats env
beats default-on). `=0` restores the untracked behavior EXACTLY — no
ledger object anywhere, `device_put` results flow through untouched,
no `memory` section — the A/B baseline `tests/test_hbm_ledger.py`
asserts. Hot-path cost at default settings is one dict store + one
dict pop + one counter bump per window (the <1% guard microbench in
the tests bounds it); `hold()` itself runs only at build/overlay/
cursor-adopt time.

`tools/hbm_report.py` fits per-subscription byte costs from this
ledger at several table sizes and extrapolates the subscription
ceiling per HBM budget — the capacity-forecast leg of ISSUE 8.
"""

from __future__ import annotations

import os
import threading
import weakref
from typing import Optional

SCHEMA = "emqx_tpu.memory/v1"


def resolve_hbm_ledger(configured=None) -> bool:
    """The one ledger-knob resolution: config (``broker.hbm_ledger``)
    beats ``EMQX_TPU_HBM_LEDGER`` beats default-on. ``=0`` restores the
    pre-ISSUE-8 untracked behavior exactly (no ledger anywhere) — the
    A/B baseline."""
    if configured is not None:
        return bool(configured)
    return os.environ.get("EMQX_TPU_HBM_LEDGER", "1") \
        not in ("0", "false", "off")


def resolve_pin_warn_windows(configured=None) -> int:
    """Stale-pin threshold, in windows: config
    (``broker.pin_warn_windows``) beats ``EMQX_TPU_PIN_WARN_WINDOWS``
    beats the built-in 64 (a healthy handle lives ~pipeline-depth
    windows, i.e. single digits; 64 is an order of magnitude of slack).
    Must be a positive integer — anything else is a deployment error
    worth failing loudly on."""
    if configured is None:
        env = os.environ.get("EMQX_TPU_PIN_WARN_WINDOWS")
        if env is None:
            return 64
        configured = env
    try:
        val = int(configured)
    except (TypeError, ValueError):
        raise ValueError(
            f"EMQX_TPU_PIN_WARN_WINDOWS={configured!r} is not an integer")
    if val <= 0:
        raise ValueError(
            f"EMQX_TPU_PIN_WARN_WINDOWS must be > 0, got {val}")
    return val


def device_memory_stats() -> Optional[dict]:
    """`memory_stats()` of the first local device, JSON-safe, or None
    where the backend does not expose it (XLA CPU returns None; TPU
    runtimes report bytes_in_use / peak_bytes_in_use / bytes_limit).
    Never raises and never forces a backend init of its own — callers
    (telemetry snapshot, bench rows) treat None as 'not available'."""
    import sys
    if "jax" not in sys.modules:
        return None     # never force a jax import from telemetry
    try:
        import jax
        ms = jax.local_devices()[0].memory_stats()
    except Exception:   # noqa: BLE001 — telemetry must never raise
        return None
    if not ms:
        return None
    return {k: int(v) for k, v in ms.items()
            if isinstance(v, (int, float))}


def total_bytes_in_use() -> Optional[int]:
    """Summed `bytes_in_use` over ALL local devices, or None where the
    backend exposes no memory_stats. The accounted-fraction denominator:
    ledger leaves are (possibly sharded) global arrays whose `.nbytes`
    spans every shard, so comparing against one device's bytes_in_use
    would overstate the fraction by the shard count on a mesh."""
    import sys
    if "jax" not in sys.modules:
        return None
    try:
        import jax
        total = 0
        seen = False
        for d in jax.local_devices():
            ms = d.memory_stats()
            if ms and "bytes_in_use" in ms:
                total += int(ms["bytes_in_use"])
                seen = True
        return total if seen else None
    except Exception:   # noqa: BLE001 — telemetry must never raise
        return None


def _leaves(tree):
    """Yield the array leaves (anything with .nbytes) of a pytree of
    tuples/NamedTuples/lists/dicts — structure-only walk, no jax import
    (the ledger must stay importable on nodes without jax)."""
    if tree is None:
        return
    if hasattr(tree, "nbytes"):
        yield tree
    elif isinstance(tree, (tuple, list)):
        for x in tree:
            yield from _leaves(x)
    elif isinstance(tree, dict):
        for x in tree.values():
            yield from _leaves(x)


class _Cat:
    """One category's accounting row."""

    __slots__ = ("live_bytes", "peak_bytes", "holds", "releases",
                 "live_leaves", "owners")

    def __init__(self):
        self.live_bytes = 0
        self.peak_bytes = 0
        self.holds = 0
        self.releases = 0
        self.live_leaves = 0
        self.owners: dict[str, int] = {}


class HbmLedger:
    """Per-node device-memory ledger (see module docstring).

    Thread-safety: ``hold()`` runs on the loop AND executor threads
    (build/warm/mesh threads), and the weakref finalizers fire on
    whatever thread drops the last reference — all category mutation
    is under one lock. ``pin``/``unpin``/``note_window`` are the only
    per-window operations and are plain dict/int ops under the GIL.
    """

    def __init__(self, metrics=None, *, pin_warn_windows=None,
                 hooks=None, recorder=None):
        self.metrics = metrics
        self.hooks = hooks
        # flight recorder (ISSUE 7): stale-pin instant events land on
        # the pinned window's causal timeline (node scope when unknown)
        self.recorder = recorder
        self.pin_warn_windows = resolve_pin_warn_windows(pin_warn_windows)
        # RLock, not Lock: weakref finalizers run at arbitrary
        # allocation points (cyclic GC), including while THIS thread
        # is inside a locked region — a reentrant _release must not
        # deadlock against the hold()/section() that triggered it
        self._lock = threading.RLock()
        self._cats: dict[str, _Cat] = {}
        # true global high-water mark of summed live bytes — NOT the
        # sum of per-category peaks (categories peak at different
        # times; that sum can report a total that never occurred)
        self._peak_bytes = 0
        # leaf id -> (category, nbytes, owner): the live set the
        # finalizers release from; id() identity dedups aliased holds
        self._live: dict[int, tuple[str, int, Optional[str]]] = {}
        # pinned dispatch handles: key -> [start_window, handle, warned]
        self._pins: dict[int, list] = {}
        self._window_clock = 0
        self.pin_warnings = 0

    # ---- holds -----------------------------------------------------------
    def hold(self, category: str, tree, owner: Optional[str] = None):
        """Register a persistent device pytree under `category` and
        return it unchanged. Every leaf gets a weakref finalizer, so
        the bytes release automatically when the arrays die — no
        explicit release call exists, by design (an unpaired release
        API is exactly the leak class this ledger hunts)."""
        total = 0
        new_leaves = 0
        with self._lock:
            cat = self._cats.setdefault(category, _Cat())
            for leaf in _leaves(tree):
                lid = id(leaf)
                if lid in self._live:
                    continue        # aliased leaf: already accounted
                nb = int(leaf.nbytes)
                try:
                    weakref.finalize(leaf, self._release, lid)
                except TypeError:
                    # not weakref-able (exotic leaf): skip rather than
                    # leak a live entry that can never release
                    continue
                self._live[lid] = (category, nb, owner)
                cat.live_bytes += nb
                cat.live_leaves += 1
                total += nb
                new_leaves += 1
                if owner is not None:
                    cat.owners[owner] = cat.owners.get(owner, 0) + nb
            cat.peak_bytes = max(cat.peak_bytes, cat.live_bytes)
            cat.holds += 1
            self._peak_bytes = max(
                self._peak_bytes,
                sum(c.live_bytes for c in self._cats.values()))
        # per-LEAF, matching _release's per-finalizer count: holds -
        # releases == live leaves, so the pair reconciles to zero like
        # the byte pair does (the category row's `holds` stays a
        # per-call activity count — see module docstring)
        if self.metrics is not None and new_leaves:
            self.metrics.inc("pipeline.memory.holds", new_leaves)
            self.metrics.inc("pipeline.memory.hold_bytes", total)
        return tree

    def _release(self, lid: int) -> None:
        """Finalizer: one leaf died — return its bytes."""
        with self._lock:
            entry = self._live.pop(lid, None)
            if entry is None:
                return
            category, nb, owner = entry
            cat = self._cats.get(category)
            if cat is not None:
                cat.live_bytes -= nb
                cat.live_leaves -= 1
                cat.releases += 1
                if owner is not None:
                    left = cat.owners.get(owner, 0) - nb
                    if left > 0:
                        cat.owners[owner] = left
                    else:
                        cat.owners.pop(owner, None)
        if self.metrics is not None:
            self.metrics.inc("pipeline.memory.releases")
            self.metrics.inc("pipeline.memory.release_bytes", nb)

    def live_bytes(self, category: Optional[str] = None) -> int:
        with self._lock:
            if category is not None:
                cat = self._cats.get(category)
                return cat.live_bytes if cat is not None else 0
            return sum(c.live_bytes for c in self._cats.values())

    def live_leaves(self) -> int:
        """Live finalizer-tracked leaves — the weakref-leak probe the
        lifecycle tests assert returns to baseline after a swap."""
        with self._lock:
            return len(self._live)

    # ---- pin sentinel (ISSUE 8 satellite) --------------------------------
    def note_window(self) -> None:
        """One prepared dispatch window: advance the pin clock and fire
        the stale-pin sentinel for any handle pinned past the
        threshold. Hot path: one int bump plus a scan of the (pipeline-
        depth-sized) pin dict."""
        self._window_clock += 1
        w = self._window_clock
        warn = self.pin_warn_windows
        for key, rec in list(self._pins.items()):
            if rec[2] or w - rec[0] <= warn:
                continue
            rec[2] = True
            self.pin_warnings += 1
            age = w - rec[0]
            handle = rec[1]() if rec[1] is not None else None
            trace = getattr(handle, "trace", 0) or 0
            if self.metrics is not None:
                self.metrics.inc("pipeline.memory.pin_warnings")
            if self.recorder is not None:
                try:
                    self.recorder.event(
                        trace, "stale_pin", track="memory",
                        meta={"age_windows": age, "warn_windows": warn})
                except Exception:  # noqa: BLE001 — sentinel best-effort
                    pass
            if self.hooks is not None:
                try:
                    self.hooks.run("pipeline.pin_stale",
                                   ({"age_windows": age,
                                     "warn_windows": warn,
                                     "trace": trace},))
                except Exception:  # noqa: BLE001 — sentinel best-effort
                    pass

    def pin(self, key: int, handle=None) -> None:
        """A dispatch handle went in flight: it pins its snapshot (the
        engine defers swaps while any pin is outstanding). Held by
        weakref only — a leaked handle (the exact case the sentinel
        hunts) must stay collectable, or the ledger itself would
        retain the snapshot HBM it is instrumenting."""
        try:
            ref = weakref.ref(handle) if handle is not None else None
        except TypeError:
            ref = None
        self._pins[key] = [self._window_clock, ref, False]

    def unpin(self, key: int) -> None:
        self._pins.pop(key, None)

    def pin_state(self) -> dict:
        w = self._window_clock
        # snapshot: pin()/unpin() mutate from loop + executor threads
        ages = [w - rec[0] for rec in list(self._pins.values())]
        return {"outstanding": len(ages),
                "max_age_windows": max(ages) if ages else 0,
                "warn_windows": self.pin_warn_windows,
                "warnings": self.pin_warnings,
                "window_clock": w}

    # ---- export surfaces -------------------------------------------------
    def section(self) -> dict:
        """The `memory` section of `PipelineTelemetry.snapshot()` —
        the one schema shared by $SYS `pipeline/memory`,
        `GET /api/v5/pipeline/memory`, bench rows and
        `tools/hbm_report.py`."""
        with self._lock:
            cats = {}
            for name in sorted(self._cats):
                c = self._cats[name]
                row = {"live_bytes": c.live_bytes,
                       "peak_bytes": c.peak_bytes,
                       "holds": c.holds, "releases": c.releases,
                       "live_leaves": c.live_leaves}
                if c.owners:
                    row["owners"] = dict(sorted(c.owners.items()))
                cats[name] = row
            live = sum(c.live_bytes for c in self._cats.values())
            peak = self._peak_bytes
        out = {"schema": SCHEMA, "live_bytes": live, "peak_bytes": peak,
               "categories": cats, "pins": self.pin_state()}
        dev = device_memory_stats()
        if dev is not None:
            out["device"] = dev
            # the backend cross-check: how much of what the devices
            # report in use the ledger can name. Well below 1 under
            # load = allocations bypassing the ledger. Denominator is
            # summed over ALL local devices: ledger leaves are global
            # arrays, so one device's bytes_in_use would overstate the
            # fraction by the shard count on a mesh.
            in_use = total_bytes_in_use()
            if in_use:
                out["accounted_fraction"] = round(live / in_use, 4)
        return out

    def stats_fun(self, stats) -> None:
        """Point-in-time gauges for the Stats table (sampled each
        sweep): the Prometheus/StatsD/$SYS-stats carriers of live
        state a counter can't express."""
        with self._lock:
            live = sum(c.live_bytes for c in self._cats.values())
            peak = self._peak_bytes
        ps = self.pin_state()
        stats.setstat("pipeline.memory.live_bytes", live)
        stats.setstat("pipeline.memory.peak_bytes", peak)
        stats.setstat("pipeline.memory.pinned_handles",
                      ps["outstanding"])
        stats.setstat("pipeline.memory.max_pin_age_windows",
                      ps["max_age_windows"])
