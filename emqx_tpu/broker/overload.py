"""Adaptive overload protection: the graded load-shed ladder (ISSUE 14).

The reference broker survives sustained floods not because every path
is fast but because ``emqx_olp`` / ``force_shutdown`` / ``force_gc``
shed load before the VM falls over. PR 6's supervisor handles *faults*
(a stage dying); this module handles *overload* — every stage healthy,
demand > capacity — closing the loop on the pressure signals the repo
already measures: batcher queue/journal depth and ``_inflight`` fill
(PR 6/9), delivery-lane depth and ``backpressure_waits`` (PR 5), the
SLO error-budget burn (PR 13), HBM ``live_bytes`` vs the device limit
(PR 8), plus a new event-loop-lag probe (housekeeping cadence drift).

**The grade ladder** — polled on the node housekeeping tick::

    normal → elevated → overload → critical

with hysteresis on BOTH edges (``up_sustain`` consecutive
above-grade polls to climb one grade, ``down_sustain`` consecutive
healthy polls to step down one) so a flapping signal cannot oscillate
the ladder. Each grade arms a documented, ORDERED set of shedding
actions, cheapest first; recovery unwinds them in reverse::

    grade      armed actions (cumulative, in arm order)
    elevated   clamp_sampling      trace per-message sampling 1-in-N
                                   × CLAMP_FACTOR; latency observatory
                                   records 1-in-CLAMP_FACTOR (burn is a
                                   breach FRACTION, so uniform sampling
                                   keeps the burn signal unbiased)
    overload   shrink_dispatch     batcher dispatch_depth → 1 (fewer
                                   in-flight windows pin fewer buffers)
               defer_retained      retained-message replay on SUBSCRIBE
                                   queues (bounded) until recovery
               pause_connects      extra acceptor lanes stop accepting;
                                   new CONNECTs answered with the v5
                                   reason 0x97 (quota exceeded)
    critical   shed_qos0           QoS0 PUBLISHes dropped at batcher
                                   admit — QoS1/2 are NEVER shed:
                                   at-least-once intent is honored and
                                   per-session order preserved (twin-
                                   tested)
               disconnect_offenders  force_shutdown parity: each poll
                                   disconnects the top-offender
                                   connection(s) by limiter debt
                                   (ingress-volume fallback when no
                                   rate limit is configured)

Every arm/unwind is individually counted (``pipeline.overload.*``),
fires the ``overload.shed`` hook (apps/tracer logs it, apps/sys
republishes the alarm), raises/updates the ``overload`` ``$SYS`` alarm
via alarm.py, and lands an ``overload_shed`` instant event on the
flight recorder (on the most recent window's trace, so the causal
timeline shows WHEN the ladder moved relative to the windows that
drove it).

**Determinism for chaos**: the PR 6 injector grammar gains two
overload points — ``signal_spike`` (a fired clause forces the raw
grade to critical this poll) and ``stuck_grade`` (a fired clause
blocks grade transitions; sustained blocking raises the
``overload_stuck`` alarm) — so tools/chaos_bench.py drives grade
climbs, sheds and recovery deterministically.

Knob: ``broker.overload`` / ``EMQX_TPU_OVERLOAD`` (config beats env
beats default-on); ``=0`` restores the pre-ISSUE-14 behavior exactly —
no governor object anywhere, no ``overload`` telemetry section, REST
``/pipeline/overload`` 404, bit-identical delivery counts and order
(A/B twin-tested).

Exported four ways like every section: ``overload`` in
``PipelineTelemetry.snapshot()`` ($SYS ``pipeline/overload``), the
``pipeline.overload.*`` counters ride the shared registry (Prometheus/
StatsD) and ``GET /api/v5/pipeline/overload``. ``tools/
overload_bench.py`` is the acceptance drive: a sustained real-TCP
overdrive flood where governor-on holds the routed p99 inside the SLO
shedding ONLY QoS0 while governor-off saturates.
"""

from __future__ import annotations

import logging
import os
import time
import weakref
from typing import Optional

log = logging.getLogger("emqx.overload")

# the grade ladder
GRADE_NORMAL = 0
GRADE_ELEVATED = 1
GRADE_OVERLOAD = 2
GRADE_CRITICAL = 3
GRADES = ("normal", "elevated", "overload", "critical")

# the ordered shed actions (cheapest first — the arm order; unwind runs
# in reverse) and the cumulative set each grade arms
ACTIONS = ("clamp_sampling", "shrink_dispatch", "defer_retained",
           "pause_connects", "shed_qos0", "disconnect_offenders")
GRADE_ACTIONS = {
    GRADE_NORMAL: ACTIONS[:0],
    GRADE_ELEVATED: ACTIONS[:1],
    GRADE_OVERLOAD: ACTIONS[:4],
    GRADE_CRITICAL: ACTIONS[:6],
}

# trace / latency sampling clamp under elevated+ (documented shed:
# per-message observability thins out 16x, window spans stay exact)
CLAMP_FACTOR = 16

# signal → grade-vote thresholds: each signal votes the HIGHEST tier
# whose threshold it meets; the raw grade is the max vote. Tuples are
# (elevated, overload, critical); None = the signal never votes that
# tier. Documented in docs/ROBUSTNESS.md — change both together.
THRESHOLDS = {
    # batcher submit-queue fill (len(_queue) / max_pending): the
    # primary demand>capacity signal — backpressure engages at 1.0
    "queue_fill": (0.50, 0.75, 0.90),
    # batcher pipeline-queue fill (_inflight.qsize / pipeline_depth)
    "inflight_fill": (1.0, None, None),
    # supervisor window-journal depth (admitted, unsettled windows)
    "journal_depth": (16, 64, 256),
    # delivery-lane plan fill (live_plans / depth_limit)
    "lane_fill": (1.0, None, None),
    # lane backpressure waits SINCE THE LAST POLL
    "backpressure_delta": (1, 50, None),
    # SLO error-budget burn (PR 13): the classic multi-window pairs —
    # 1m alone warns; 1m AND 5m page-level (>=14) is overload; a 1m
    # burn of >=50 with the 5m window confirming is critical
    "burn_1m": (1.0, None, None),
    "burn_page": (None, 14.0, 50.0),     # min(burn_1m, burn_5m)
    # HBM pressure (PR 8): ledger live_bytes / device bytes_limit
    "hbm_fill": (0.80, 0.90, 0.95),
    # event-loop lag: housekeeping cadence drift beyond the interval
    "loop_lag_s": (0.05, 0.25, 1.0),
}

# offender scores decay by half each poll so a connection that went
# quiet stops being a shed candidate within a few ticks
_SCORE_DECAY = 0.5


def resolve_overload(configured=None) -> bool:
    """The one overload-governor resolution (ISSUE 14): config
    (``broker.overload``) beats ``EMQX_TPU_OVERLOAD`` beats default-on.
    ``=0`` restores the pre-ISSUE-14 behavior exactly — no governor
    object anywhere, no ``overload`` telemetry section, REST
    ``/pipeline/overload`` 404, bit-identical delivery counts and
    per-publisher order (the A/B twin test pins all four)."""
    if configured is not None:
        return bool(configured)
    return os.environ.get("EMQX_TPU_OVERLOAD", "1") \
        not in ("0", "false", "off")


class OverloadGovernor:
    """Per-node overload state machine + the shed-action ladder.

    Hot-path contract: the serving paths read only plain bool
    attributes (``shed_qos0``, ``connects_paused``,
    ``retained_deferred``) — one attribute read per check, no locks, no
    calls. All state transitions happen in ``poll()`` on the
    housekeeping tick (event loop), so there is no cross-thread
    read-modify-write anywhere in this class."""

    def __init__(self, node, metrics, *, hooks=None, recorder=None,
                 up_sustain: int = 2, down_sustain: int = 5,
                 clamp_factor: int = CLAMP_FACTOR,
                 disconnects_per_poll: int = 1,
                 thresholds: Optional[dict] = None):
        self.node = node
        self.metrics = metrics
        self.hooks = hooks
        self.recorder = recorder
        self.up_sustain = max(1, int(up_sustain))
        self.down_sustain = max(1, int(down_sustain))
        self.clamp_factor = max(2, int(clamp_factor))
        self.disconnects_per_poll = max(1, int(disconnects_per_poll))
        self.thresholds = dict(THRESHOLDS)
        if thresholds:
            self.thresholds.update(thresholds)
        self.grade = GRADE_NORMAL
        self.grade_changes = 0
        self.grade_since = time.monotonic()
        # hot-path shed flags (plain attribute reads on serving paths)
        self.shed_qos0 = False
        self.connects_paused = False
        self.retained_deferred = False
        self._armed: list[str] = []      # in arm order
        self._saved: dict = {}           # action -> pre-shed state
        self._above = 0                  # consecutive raw>grade polls
        self._below = 0                  # consecutive raw<grade polls
        # re-breach backoff: a climb right after a step-down means the
        # easing itself re-admitted the overload (the raw signals read
        # healthy exactly BECAUSE the shed was working) — each such
        # re-breach doubles the sustained-healthy multiplier the next
        # step-down requires, up to 64x; a full recovery to normal
        # resets it. The oscillation damper of the ladder.
        self._down_mult = 1
        self._polls = 0
        self._last_down_poll: Optional[int] = None
        self.last_signals: dict = {}
        self.loop_lag_s = 0.0
        self.poll_interval_s: Optional[float] = None
        self._last_poll: Optional[float] = None
        self._last_backpressure = 0
        self._last_obs_samples = 0
        self._hbm_limit: Optional[int] = None
        self._hbm_limit_probed = False
        self.stuck_polls = 0
        self._stuck_alarmed = False
        # live-connection registry for the top-offender shed: weak so
        # the governor can never keep a dead connection's buffers alive
        self._conns: "weakref.WeakSet" = weakref.WeakSet()

    # ---- connection registry (force_shutdown parity) --------------------
    def register_conn(self, conn) -> None:
        self._conns.add(conn)

    # ---- fault injection (the PR 6 grammar's overload points) -----------
    def _fire(self, point: str) -> bool:
        """Traverse an overload injection point. ANY fired clause is
        the condition (the recommended kind is ``corrupt`` — it fires
        without raising; exception/resource clauses are caught and
        count the same; a hang clause blocks the tick like a real
        loop stall would, then counts)."""
        sup = getattr(self.node, "supervisor", None)
        if sup is None or not sup.injector.armed():
            return False
        try:
            return sup.fire(point, corrupt_ok=True) is not None
        except Exception:  # noqa: BLE001 — raising kinds: same signal
            return True

    # ---- signal sampling -------------------------------------------------
    def sample_signals(self) -> dict:
        """One poll's raw signal readings — every input already exists
        in the pipeline; this only reads, never computes. Tests
        monkeypatch this to drive the ladder deterministically."""
        node = self.node
        s: dict = {}
        b = getattr(node, "publish_batcher", None)
        if b is not None:
            s["queue_fill"] = round(
                len(b._queue) / max(1, b.max_pending), 4)
            q = b._inflight
            if q is not None:
                s["inflight_fill"] = round(
                    q.qsize() / max(1, b.pipeline_depth), 4)
        sup = getattr(node, "supervisor", None)
        if sup is not None:
            s["journal_depth"] = sup.journal_depth()
        pool = getattr(node, "deliver_lanes", None)
        if pool is not None:
            st = pool.state()
            s["lane_fill"] = round(
                st["live_plans"] / max(1, st["depth_limit"]), 4)
            waits = self.metrics.val("pipeline.deliver.backpressure_waits")
            s["backpressure_delta"] = waits - self._last_backpressure
            self._last_backpressure = waits
        obs = getattr(node, "latency_observatory", None)
        if obs is not None:
            ns = obs.samples
            if ns > self._last_obs_samples:
                # burn contributes only while traffic is LIVE: the
                # windows look back 1m/5m, so a flood that already
                # drained would otherwise hold the ladder up for a
                # full window with the broker idle — burn measures the
                # current spend rate, and an idle broker spends nothing
                burn = obs.burn_rates()
                s["burn_1m"] = burn.get("1m", 0.0)
                s["burn_page"] = min(burn.get("1m", 0.0),
                                     burn.get("5m", 0.0))
            self._last_obs_samples = ns
        led = getattr(node, "hbm_ledger", None)
        if led is not None:
            if not self._hbm_limit_probed:
                self._hbm_limit_probed = True
                from emqx_tpu.broker.hbm_ledger import device_memory_stats
                dev = device_memory_stats() or {}
                self._hbm_limit = dev.get("bytes_limit")
            if self._hbm_limit:
                s["hbm_fill"] = round(
                    led.live_bytes() / self._hbm_limit, 4)
        s["loop_lag_s"] = round(self.loop_lag_s, 4)
        return s

    def _grade_of(self, signals: dict) -> int:
        raw = GRADE_NORMAL
        for name, val in signals.items():
            t = self.thresholds.get(name)
            if t is None or val is None:
                continue
            for tier in (GRADE_CRITICAL, GRADE_OVERLOAD, GRADE_ELEVATED):
                bound = t[tier - 1]
                if bound is not None and val >= bound:
                    raw = max(raw, tier)
                    break
        return raw

    # ---- the poll (housekeeping tick) ------------------------------------
    def poll(self, now: Optional[float] = None) -> None:
        now = time.monotonic() if now is None else now
        # event-loop-lag probe: cadence drift of this very tick. The
        # housekeeping sleep targets poll_interval_s; anything beyond
        # it is time the loop spent wedged in callbacks.
        if self._last_poll is not None and self.poll_interval_s:
            self.loop_lag_s = max(
                0.0, (now - self._last_poll) - self.poll_interval_s)
        self._last_poll = now
        spike = self._fire("signal_spike")
        stuck = self._fire("stuck_grade")
        signals = self.sample_signals()
        raw = GRADE_CRITICAL if spike else self._grade_of(signals)
        self.last_signals = dict(signals, raw=raw)
        if spike:
            self.last_signals["signal_spike"] = True
        self._polls += 1
        if raw > self.grade:
            self._below = 0
            self._above += 1
            if self._above >= self.up_sustain:
                self._above = 0
                rebreach = self._last_down_poll is not None \
                    and self._polls - self._last_down_poll \
                    <= self.up_sustain + 2
                # backoff bookkeeping only when the climb actually
                # happens — a stuck_grade-blocked transition must not
                # double the multiplier for an easing that never was
                if self._transition(self.grade + 1, stuck) and rebreach:
                    # re-breach right after easing: back the next
                    # step-down off (doubling, capped) — see
                    # _down_mult above
                    self._down_mult = min(self._down_mult * 2, 64)
                    self.metrics.inc("pipeline.overload.rebreaches")
        elif raw < self.grade:
            self._above = 0
            self._below += 1
            if self._below >= self.down_sustain * self._down_mult:
                self._below = 0
                if self._transition(self.grade - 1, stuck):
                    self._last_down_poll = self._polls
                    if self.grade == GRADE_NORMAL:
                        self._down_mult = 1
                        self._last_down_poll = None
        else:
            self._above = self._below = 0
            if self._stuck_alarmed and not stuck:
                self._clear_stuck()
        if self.grade >= GRADE_CRITICAL \
                and "disconnect_offenders" in self._armed:
            self._shed_offenders()
        self._decay_scores()

    def _transition(self, new_grade: int, stuck: bool) -> bool:
        """Apply a due grade change; returns True when the grade
        actually moved (False = blocked by a stuck_grade clause) so
        the caller's backoff bookkeeping tracks only real easings."""
        if stuck:
            # the stuck_grade injection (or a future real wedge hook):
            # a transition was DUE but blocked — count it, and after
            # the ladder stays frozen for a sustained interval raise
            # the overload_stuck alarm (the chaos cell's oracle)
            self.stuck_polls += 1
            self.metrics.inc("pipeline.overload.stuck_polls")
            if self.stuck_polls >= 3 and not self._stuck_alarmed:
                self._stuck_alarmed = True
                alarms = getattr(self.node, "alarms", None)
                if alarms is not None:
                    alarms.activate(
                        "overload_stuck",
                        {"grade": GRADES[self.grade],
                         "stuck_polls": self.stuck_polls},
                        "overload governor grade transitions blocked")
            return False
        old = self.grade
        self.grade = new_grade
        self.grade_since = time.monotonic()
        self.grade_changes += 1
        self.metrics.inc("pipeline.overload.grade_changes")
        self._apply_actions()
        self._update_alarm()
        if self.recorder is not None:
            self.recorder.event(
                self._trace(), "overload_grade", track="overload",
                meta={"from": GRADES[old], "to": GRADES[new_grade],
                      "signals": dict(self.last_signals)})
        lvl = logging.WARNING if new_grade > old else logging.INFO
        log.log(lvl, "overload grade %s -> %s (signals %s; armed %s)",
                GRADES[old], GRADES[new_grade], self.last_signals,
                self._armed)
        return True

    def _clear_stuck(self) -> None:
        self.stuck_polls = 0
        self._stuck_alarmed = False
        alarms = getattr(self.node, "alarms", None)
        if alarms is not None:
            alarms.deactivate("overload_stuck")

    def _trace(self) -> int:
        """The most recent window's trace id (minted at batcher admit)
        — shed events land on the window timeline they interleave
        with; 0 (node scope) when no window is in flight."""
        b = getattr(self.node, "publish_batcher", None)
        return getattr(b, "last_trace", 0) if b is not None else 0

    # ---- the action ladder ----------------------------------------------
    def _apply_actions(self) -> None:
        want = GRADE_ACTIONS[self.grade]
        for a in ACTIONS:                    # arm cheapest-first
            if a in want and a not in self._armed:
                self._arm(a)
        for a in reversed(ACTIONS):          # unwind in reverse order
            if a in self._armed and a not in want:
                self._unarm(a)

    def _arm(self, action: str) -> None:
        node = self.node
        if action == "clamp_sampling":
            rec = getattr(node, "flight_recorder", None)
            if rec is not None and rec.sample > 0:
                self._saved["trace_sample"] = rec.sample
                rec.sample = rec.sample * self.clamp_factor
            obs = getattr(node, "latency_observatory", None)
            if obs is not None:
                self._saved["latency_clamp"] = obs.clamp
                obs.clamp = self.clamp_factor
        elif action == "shrink_dispatch":
            b = getattr(node, "publish_batcher", None)
            if b is not None:
                self._saved["dispatch_depth"] = b.dispatch_depth
                b.dispatch_depth = 1
        elif action == "defer_retained":
            self.retained_deferred = True
        elif action == "pause_connects":
            self.connects_paused = True
        elif action == "shed_qos0":
            self.shed_qos0 = True
        # disconnect_offenders: armed flag only — the disconnects
        # themselves run once per poll while critical (rate-bounded)
        self._armed.append(action)
        self._note_shed(action, armed=True)

    def _unarm(self, action: str) -> None:
        node = self.node
        if action == "clamp_sampling":
            rec = getattr(node, "flight_recorder", None)
            saved = self._saved.pop("trace_sample", None)
            if rec is not None and saved is not None:
                rec.sample = saved
            obs = getattr(node, "latency_observatory", None)
            saved = self._saved.pop("latency_clamp", None)
            if obs is not None and saved is not None:
                obs.clamp = saved
        elif action == "shrink_dispatch":
            b = getattr(node, "publish_batcher", None)
            saved = self._saved.pop("dispatch_depth", None)
            if b is not None and saved is not None:
                b.dispatch_depth = saved
        elif action == "defer_retained":
            self.retained_deferred = False
        elif action == "pause_connects":
            self.connects_paused = False
        elif action == "shed_qos0":
            self.shed_qos0 = False
        self._armed.remove(action)
        self._note_shed(action, armed=False)

    def _note_shed(self, action: str, armed: bool) -> None:
        m = self.metrics
        if armed:
            m.inc("pipeline.overload.sheds")
            m.inc(f"pipeline.overload.actions.{action}")
        info = {"action": action, "armed": armed,
                "grade": GRADES[self.grade]}
        if self.hooks is not None:
            self.hooks.run("overload.shed", (info,))
        if self.recorder is not None:
            self.recorder.event(self._trace(), "overload_shed",
                                track="overload", meta=info)

    def _update_alarm(self) -> None:
        """The ``overload`` $SYS alarm rides alarm.py: active above
        normal (details refreshed per grade change — deactivate +
        activate, so the history records every grade the flood
        visited), cleared on recovery."""
        alarms = getattr(self.node, "alarms", None)
        if alarms is None:
            return
        alarms.deactivate("overload")
        if self.grade > GRADE_NORMAL:
            alarms.activate(
                "overload",
                {"grade": GRADES[self.grade],
                 "actions": list(self._armed),
                 "signals": dict(self.last_signals)},
                f"broker overloaded: grade {GRADES[self.grade]}")

    # ---- hot-path accounting (called by the shedding sites) -------------
    def count_qos0_shed(self, n: int = 1) -> None:
        self.metrics.inc("pipeline.overload.qos0_shed", n)

    def count_connect_rejected(self) -> None:
        self.metrics.inc("pipeline.overload.connects_rejected")

    def count_accept_paused(self) -> None:
        self.metrics.inc("pipeline.overload.accepts_paused")

    def count_retained_deferred(self, n: int = 1) -> None:
        self.metrics.inc("pipeline.overload.retained_deferred", n)

    # ---- top-offender disconnect (force_shutdown parity) ----------------
    def _shed_offenders(self) -> None:
        scored = []
        for conn in list(self._conns):
            score = conn.shed_score()
            if score > 0:
                scored.append((score, id(conn), conn))
        if not scored:
            return
        scored.sort(reverse=True)
        for score, _cid, conn in scored[:self.disconnects_per_poll]:
            self.metrics.inc("pipeline.overload.disconnects")
            info = {"action": "disconnect_offender", "armed": True,
                    "grade": GRADES[self.grade],
                    "clientid": conn.channel.clientid,
                    "debt": round(score, 3)}
            if self.hooks is not None:
                self.hooks.run("overload.shed", (info,))
            log.warning("overload: disconnecting top offender %r "
                        "(debt %.3f)", conn.channel.clientid, score)
            conn.overload_disconnect()
            self._conns.discard(conn)

    def _decay_scores(self) -> None:
        for conn in list(self._conns):
            conn.shed_rows *= _SCORE_DECAY

    # ---- telemetry -------------------------------------------------------
    def state(self) -> dict:
        """Live gauges for the ``overload`` telemetry section (the
        counters ride the shared Metrics registry)."""
        return {
            "grade": GRADES[self.grade],
            "grade_num": self.grade,
            "since_s": round(time.monotonic() - self.grade_since, 1),
            "actions": list(self._armed),
            "signals": dict(self.last_signals),
            "hysteresis": {"above": self._above, "below": self._below,
                           "up_sustain": self.up_sustain,
                           "down_sustain": self.down_sustain,
                           "down_mult": self._down_mult},
            "loop_lag_ms": round(self.loop_lag_s * 1000, 2),
            "conns_tracked": len(self._conns),
            "stuck_polls": self.stuck_polls,
        }
