"""Bounded pending-message queue with per-topic priorities.

Parity: emqx_mqueue.erl — drop-oldest-on-full priority queue holding
messages awaiting delivery while the inflight window is closed; optional
per-topic priorities and a store_qos0 toggle (emqx_mqueue.erl:44,75-88).
"""

from __future__ import annotations

from collections import deque
from dataclasses import dataclass, field
from typing import Optional

from emqx_tpu.broker.message import Message

DEFAULT_PRIORITY = 0


@dataclass
class MQueueOpts:
    max_len: int = 1000                 # 0 = unlimited
    store_qos0: bool = True
    priorities: dict = field(default_factory=dict)  # topic -> int (higher first)
    default_priority: str = "lowest"    # 'lowest' | 'highest' for unlisted topics


class MQueue:
    """Priority buckets of FIFO deques; drop-oldest across lowest priority."""

    def __init__(self, opts: Optional[MQueueOpts] = None):
        self.opts = opts or MQueueOpts()
        self._qs: dict[int, deque] = {}   # priority -> deque[Message]
        self._len = 0
        self.dropped = 0

    def __len__(self) -> int:
        return self._len

    def is_empty(self) -> bool:
        return self._len == 0

    def max_len(self) -> int:
        return self.opts.max_len

    def _priority(self, topic: str) -> int:
        if topic in self.opts.priorities:
            return self.opts.priorities[topic]
        if not self.opts.priorities:
            return DEFAULT_PRIORITY
        if self.opts.default_priority == "highest":
            return max(self.opts.priorities.values()) + 1
        return min(self.opts.priorities.values()) - 1

    def insert(self, msg: Message) -> Optional[Message]:
        """Enqueue; returns the dropped message if the queue was full
        (parity: emqx_mqueue:in/2 returning {Dropped, Q})."""
        if msg.qos == 0 and not self.opts.store_qos0:
            self.dropped += 1
            return msg
        prio = self._priority(msg.topic)
        q = self._qs.setdefault(prio, deque())
        dropped = None
        if self.opts.max_len and self._len >= self.opts.max_len:
            dropped = self._drop_oldest()
        q.append(msg)
        self._len += 1
        return dropped

    def insert_front(self, msg: Message) -> None:
        """Put a message at the head of its priority bucket (used when
        shrinking the inflight window on resume — these were already
        inflight, so they precede everything queued later). Never drops."""
        prio = self._priority(msg.topic)
        self._qs.setdefault(prio, deque()).appendleft(msg)
        self._len += 1

    def _drop_oldest(self) -> Optional[Message]:
        for prio in sorted(self._qs):
            q = self._qs[prio]
            if q:
                self._len -= 1
                self.dropped += 1
                return q.popleft()
        return None

    def out(self) -> Optional[Message]:
        """Dequeue highest-priority oldest message (emqx_mqueue:out/1)."""
        for prio in sorted(self._qs, reverse=True):
            q = self._qs[prio]
            if q:
                self._len -= 1
                return q.popleft()
        return None

    def to_list(self) -> list[Message]:
        out = []
        for prio in sorted(self._qs, reverse=True):
            out.extend(self._qs[prio])
        return out

    def filter(self, pred) -> int:
        """Drop messages failing pred; returns count dropped (expiry sweep)."""
        removed = 0
        for q in self._qs.values():
            keep = [m for m in q if pred(m)]
            removed += len(q) - len(keep)
            q.clear()
            q.extend(keep)
        self._len -= removed
        self.dropped += removed
        return removed

    def stats(self) -> dict:
        return {"len": self._len, "max_len": self.opts.max_len,
                "dropped": self.dropped}
