"""In-flight (unacked outbound QoS1/2) send window.

Parity: emqx_inflight.erl — gb_trees send window keyed by packet id, with
a max size gating dequeue from the mqueue. Python dicts preserve insertion
order, giving the same oldest-first retry iteration the gb_tree provides.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Any, Iterator, Optional


@dataclass
class InflightEntry:
    value: Any            # (phase, Message) — 'publish' awaiting PUBACK/PUBREC,
                          # 'pubrel' awaiting PUBCOMP
    ts: float             # last (re)send time, for retry


class Inflight:
    def __init__(self, max_size: int = 32):
        self.max_size = max_size          # 0 = unlimited
        self._d: dict[int, InflightEntry] = {}

    def __len__(self) -> int:
        return len(self._d)

    def is_full(self) -> bool:
        return self.max_size != 0 and len(self._d) >= self.max_size

    def is_empty(self) -> bool:
        return not self._d

    def contain(self, pid: int) -> bool:
        return pid in self._d

    def insert(self, pid: int, value: Any) -> None:
        if pid in self._d:
            raise KeyError(f"packet id {pid} already inflight")
        self._d[pid] = InflightEntry(value, time.monotonic())

    def update(self, pid: int, value: Any) -> None:
        self._d[pid] = InflightEntry(value, time.monotonic())

    def lookup(self, pid: int) -> Optional[Any]:
        e = self._d.get(pid)
        return e.value if e else None

    def delete(self, pid: int) -> Optional[Any]:
        e = self._d.pop(pid, None)
        return e.value if e else None

    def items(self) -> Iterator[tuple[int, InflightEntry]]:
        """Oldest-first (insertion order)."""
        return iter(list(self._d.items()))

    def clear(self) -> None:
        self._d.clear()
