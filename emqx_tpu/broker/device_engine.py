"""Device route engine: the fused route step wired into the serving path.

This is the piece that makes the TPU program THE broker hot path instead of
a side-car demo: it compiles the live routing state (Router filter universe +
Broker subscriber/shared-group membership) into the fused device tables
(models.router_engine), runs `route_step`/`route_step_shapes` over publish
micro-batches, and consumes the `RouteResult` into actual session deliveries
— replacing the reference's per-message publish path
(emqx_broker.erl:199-308: match_routes → dispatch fold → shared pick).

Snapshot/consistency model (SURVEY.md §7 hard-part 1, "mutable trie on
immutable arrays"):

- The compiled tables are an immutable snapshot; mutations keep flowing into
  the authoritative host dicts and are *tracked* relative to the snapshot:
  - a filter whose subscriber membership changed since the build is DIRTY —
    its fan-out segment on device is stale, so its deliveries come from the
    live host dict instead (correct for adds, removes and opts changes);
  - a filter added since the build lives in a DELTA host trie and is matched
    and dispatched host-side;
  - a (filter, group) shared slot that changed is dirty likewise; a group
    added to a built filter is dispatched host-side until the next rebuild.
- When accumulated churn crosses `rebuild_threshold` the snapshot is
  recompiled (capacities padded to pow2 size classes so XLA recompiles only
  on class growth, not on every rebuild).

Delivery attribution: device fan-out rows for one message are the
concatenation of per-filter CSR segments in match order, so the host walks
`matches[i]` and slices `rows[i]` by the *built* segment lengths — clean
filters deliver straight from device rows (packed opts unpacked on the fly),
no host dict walk. Messages flagged overflow/too-deep fall back to the full
host path (emqx_router.erl:136-141 short-circuit analog).

Shared subscriptions: device picks (ops.shared cursors) drive delivery when
the node is standalone and the strategy is device-supported (round_robin /
random / hash_*); under a cluster (remote members live off-device) or the
sticky strategy, shared dispatch stays host-side — same split as round 1
documented, now actually wired.
"""

from __future__ import annotations

import zlib
from typing import Optional

import numpy as np

from emqx_tpu.broker.message import Message
from emqx_tpu.ops import intern as I
from emqx_tpu.utils import topic as T

_PACKED_KEYS = {"qos", "nl", "rap", "rh"}


def _pack_opts(opts: dict) -> int:
    return ((int(opts.get("qos", 0)) & 0x3)
            | ((1 if opts.get("nl") else 0) << 2)
            | ((1 if opts.get("rap") else 0) << 3)
            | ((int(opts.get("rh", 0)) & 0x3) << 4))


def _unpack_opts(b: int) -> dict:
    return {"qos": b & 0x3, "nl": (b >> 2) & 1, "rap": (b >> 3) & 1,
            "rh": (b >> 4) & 0x3}


def _is_rich(opts: dict) -> bool:
    """Subopts that the packed byte cannot carry (v5 subscription ids etc.)
    force the filter onto the host dict path."""
    return any(k not in _PACKED_KEYS and k != "share" and v is not None
               for k, v in opts.items())


def _next_pow2(x: int) -> int:
    return 1 << max(2, (x - 1).bit_length())


class _Built:
    """One compiled snapshot (host-side indexes of the device tables)."""

    __slots__ = ("fid_of", "fid_filter", "seg_len", "slot_of", "slot_key",
                 "n_slots", "backend")

    def __init__(self):
        self.fid_of: dict[str, int] = {}
        self.fid_filter: list[str] = []
        self.seg_len: list[int] = []
        self.slot_of: dict[tuple, int] = {}       # (filter, group) -> slot
        self.slot_key: list[tuple] = []           # slot -> (filter, group)
        self.n_slots = 0
        self.backend = "trie"


class DeviceRouteEngine:
    def __init__(self, node, *, rebuild_threshold: int = 256,
                 max_levels: int = 16, frontier_cap: int = 16,
                 match_cap: int = 64, fanout_cap: int = 128,
                 slot_cap: int = 16, shape_cap: int = 32):
        self.node = node
        self.broker = node.broker
        self.router = node.broker.router
        self.rebuild_threshold = rebuild_threshold
        self.max_levels = max_levels
        self.frontier_cap = frontier_cap
        self.match_cap = match_cap
        self.fanout_cap = fanout_cap
        self.slot_cap = slot_cap
        self.shape_cap = shape_cap

        self.intern = I.InternTable()
        self._built: Optional[_Built] = None
        self._tables = None            # device RouterTables/ShapeRouterTables
        self._cursors = None           # device [G]
        self.dirty_filters: set[str] = set()
        self.dirty_slots: set[tuple] = set()
        self.new_slots_by_filter: dict[str, set[str]] = {}
        self.rich_filters: set[str] = set()
        from emqx_tpu.ops.trie import HostTrie
        self._delta_trie = HostTrie()
        self._delta_filter: dict[int, str] = {}
        self._delta_fid_of: dict[str, int] = {}
        self._next_delta_fid = 0

        # wire change notifications
        self.router.on_route_change = self.note_route_change
        self.broker.device_engine = self

    # ---- churn tracking -------------------------------------------------
    def staleness(self) -> int:
        """Distinct stale entities vs the snapshot (filters/slots serving
        host-side) — the rebuild trigger. A set-size measure, so repeated
        churn on one filter counts once and the subscribe path's double
        notification (route change + member change) cannot double-count."""
        return (len(self.dirty_filters) + len(self.dirty_slots)
                + len(self._delta_filter)
                + sum(len(v) for v in self.new_slots_by_filter.values()))

    def note_route_change(self, topic_filter: str, added: bool) -> None:
        """Router filter-universe change (local subscribe path and
        cluster-replicated remote routes both land here)."""
        if self._built is None:
            return
        if added:
            if topic_filter in self._built.fid_of:
                self.dirty_filters.add(topic_filter)
            elif topic_filter not in self._delta_fid_of:
                words = self.intern.encode_filter(T.tokens(topic_filter))
                fid = self._next_delta_fid
                self._next_delta_fid += 1
                self._delta_trie.insert(words, fid)
                self._delta_filter[fid] = topic_filter
                self._delta_fid_of[topic_filter] = fid
        else:
            if topic_filter in self._built.fid_of:
                self.dirty_filters.add(topic_filter)
            fid = self._delta_fid_of.pop(topic_filter, None)
            if fid is not None:
                words = self.intern.encode_filter(T.tokens(topic_filter))
                self._delta_trie.delete(words)
                self._delta_filter.pop(fid, None)

    def note_member_change(self, real: str, group: Optional[str]) -> None:
        """Broker membership change (subscribe/unsubscribe/opts update)."""
        if self._built is None:
            return
        if group is None:
            if real in self._built.fid_of:
                self.dirty_filters.add(real)
        else:
            if (real, group) in self._built.slot_of:
                self.dirty_slots.add((real, group))
            elif real in self._built.fid_of:
                self.new_slots_by_filter.setdefault(real, set()).add(group)
            # delta filters dispatch host-side entirely — nothing to track

    # ---- snapshot compile ----------------------------------------------
    def rebuild(self) -> None:
        """Compile router+broker state into fresh device tables and swap."""
        import jax

        from emqx_tpu.models.router_engine import (RouterTables,
                                                   ShapeRouterTables)
        from emqx_tpu.ops.fanout import build_subtable
        from emqx_tpu.ops.shapes import ShapeCapacityError, build_shape_tables
        from emqx_tpu.ops.trie import build_tables

        broker, router = self.broker, self.router
        filters = sorted(router.exact) + sorted(router.wildcards)
        if not filters:
            self._built = None
            self._tables = None
            self._cursors = None
            self._reset_deltas()
            return

        b = _Built()
        b.fid_of = {f: i for i, f in enumerate(filters)}
        b.fid_filter = filters
        n = len(filters)
        words = [self.intern.encode_filter(T.tokens(f)) for f in filters]
        L = max(1, max(len(w) for w in words))
        rows = np.zeros((n, L), np.int32)
        lens = np.zeros(n, np.int64)
        for i, w in enumerate(words):
            rows[i, :len(w)] = w
            lens[i] = len(w)

        normal: dict[int, list] = {}
        filter_slots: dict[int, list] = {}
        shared_members: dict[int, list] = {}
        cursors0: list[int] = []
        rich: set[str] = set()
        seg_len = [0] * n
        for f, fid in b.fid_of.items():
            subs = broker.subs.get(f)
            if subs:
                entries = []
                for sid, opts in subs.items():
                    if _is_rich(opts):
                        rich.add(f)
                    entries.append((sid, _pack_opts(opts)))
                normal[fid] = entries
                seg_len[fid] = len(entries)
            for g in sorted(broker.shared.get(f, {})):
                grp = broker.shared[f][g]
                slot = len(b.slot_key)
                b.slot_of[(f, g)] = slot
                b.slot_key.append((f, g))
                members = []
                for sid, opts in grp.members.items():
                    if _is_rich(opts):
                        rich.add(f)
                    members.append((sid, _pack_opts(opts)))
                shared_members[slot] = members
                filter_slots.setdefault(fid, []).append(slot)
                cursors0.append(grp.cursor)
        b.seg_len = seg_len
        b.n_slots = len(b.slot_key)

        # pow2 capacity classes: recompile only when a class grows
        filter_cap = _next_pow2(n)
        total_subs = sum(seg_len)
        total_members = sum(len(m) for m in shared_members.values())
        subs_tbl = build_subtable(
            filter_cap, normal, filter_slots, shared_members,
            slot_cap=_next_pow2(max(1, b.n_slots)),
            sub_rows_cap=_next_pow2(max(1, total_subs)),
            fs_rows_cap=_next_pow2(max(1, b.n_slots)),
            member_rows_cap=_next_pow2(max(1, total_members)))

        tables = None
        if L <= 20:
            try:
                st = build_shape_tables(rows, lens, shape_cap=self.shape_cap)
                tables = ShapeRouterTables(shapes=st, subs=subs_tbl)
                b.backend = "shapes"
            except ShapeCapacityError:
                tables = None
        if tables is None:
            node_cap = _next_pow2(max(256, 2 * (int(lens.sum()) + 1)))
            trie = build_tables(rows, lens, node_capacity=node_cap,
                                slot_capacity=4 * node_cap)
            tables = RouterTables(trie=trie, subs=subs_tbl)
            b.backend = "trie"

        cur = np.zeros(max(1, len(cursors0)), np.int32)
        if cursors0:
            cur[:len(cursors0)] = cursors0
        self._tables = jax.device_put(tables)
        self._cursors = jax.device_put(cur)
        self._built = b
        self.rich_filters = rich
        self._reset_deltas()
        self.node.metrics.inc("routing.device.rebuilds")

    def _reset_deltas(self) -> None:
        from emqx_tpu.ops.trie import HostTrie
        self.dirty_filters = set()
        self.dirty_slots = set()
        self.new_slots_by_filter = {}
        self._delta_trie = HostTrie()
        self._delta_filter = {}
        self._delta_fid_of = {}
        self._next_delta_fid = 0

    # ---- the serving path ----------------------------------------------
    def device_shared_active(self) -> bool:
        from emqx_tpu.ops.shared import STRATEGIES
        return (self.broker.cluster is None
                and self.broker.shared_strategy in STRATEGIES)

    def route_batch(self, msgs: list[Message]) -> Optional[list[int]]:
        """Route+deliver a micro-batch through the fused device step.

        Returns per-message delivery counts, or None when the engine has no
        tables to serve (caller falls back to the host path).
        """
        if self._built is None or self.staleness() >= self.rebuild_threshold:
            self.rebuild()
        if self._built is None:
            return None
        from emqx_tpu.models.router_engine import (route_step,
                                                   route_step_shapes)
        from emqx_tpu.ops.match import encode_topics
        from emqx_tpu.ops.shared import (STRATEGIES, STRATEGY_HASH_CLIENT,
                                         STRATEGY_HASH_TOPIC,
                                         STRATEGY_ROUND_ROBIN)

        broker = self.broker
        b = self._built
        B = len(msgs)
        # quantize the batch axis to few size classes — each class is one
        # XLA compile; without this every new pow2 batch size stalls live
        # traffic on a recompile
        for Bp in (64, 256, 1024):
            if B <= Bp:
                break
        else:
            Bp = _next_pow2(B)
        words_list = [T.tokens(m.topic) for m in msgs]
        enc, lens, dollar, too_long = encode_topics(
            self.intern, [w[:self.max_levels] for w in words_list],
            self.max_levels)
        if Bp != B:
            pad = ((0, Bp - B), (0, 0))
            enc = np.pad(enc, pad, constant_values=I.PAD)
            lens = np.pad(lens, (0, Bp - B))
            dollar = np.pad(dollar, (0, Bp - B))

        dev_shared = self.device_shared_active()
        strat_id = STRATEGIES.get(broker.shared_strategy,
                                  STRATEGY_ROUND_ROBIN)
        if strat_id == STRATEGY_HASH_TOPIC:
            mh = [zlib.crc32(m.topic.encode()) & 0x7FFFFFFF for m in msgs]
        elif strat_id == STRATEGY_HASH_CLIENT:
            mh = [zlib.crc32((m.from_ or "").encode()) & 0x7FFFFFFF
                  for m in msgs]
        elif strat_id == STRATEGY_ROUND_ROBIN:
            mh = [0] * B
        else:  # random: any per-message entropy
            mh = [(id(m) >> 4) & 0x7FFFFFFF for m in msgs]
        msg_hash = np.zeros(Bp, np.int32)
        msg_hash[:B] = mh

        if b.backend == "shapes":
            res = route_step_shapes(
                self._tables, self._cursors, enc, lens, dollar, msg_hash,
                np.int32(strat_id), fanout_cap=self.fanout_cap,
                slot_cap=self.slot_cap)
        else:
            res = route_step(
                self._tables, self._cursors, enc, lens, dollar, msg_hash,
                np.int32(strat_id), frontier_cap=self.frontier_cap,
                match_cap=self.match_cap, fanout_cap=self.fanout_cap,
                slot_cap=self.slot_cap)
        self._cursors = res.new_cursors

        matches = np.asarray(res.matches)
        rows = np.asarray(res.rows)
        opts = np.asarray(res.opts)
        shared_sids = np.asarray(res.shared_sids)
        shared_rows = np.asarray(res.shared_rows)
        shared_opts = np.asarray(res.shared_opts)
        overflow = np.asarray(res.overflow)
        if dev_shared and b.n_slots:
            self._writeback_cursors(np.asarray(res.occur))

        metrics = self.node.metrics
        counts: list[int] = []
        for i, msg in enumerate(msgs):
            if too_long[i] or overflow[i]:
                metrics.inc("routing.device.host_fallback")
                counts.append(broker._route(msg,
                                            self.router.match(msg.topic)))
                continue
            counts.append(self._consume_one(
                msg, matches[i], rows[i], opts[i], shared_sids[i],
                shared_rows[i], shared_opts[i], words_list[i], dev_shared))
        metrics.inc("routing.device.batches")
        return counts

    def _writeback_cursors(self, occur: np.ndarray) -> None:
        """Mirror device round-robin cursor advances into the host
        SharedGroup state so the host path and the next rebuild stay fair."""
        if self.broker.shared_strategy != "round_robin":
            return
        b = self._built
        for slot in np.flatnonzero(occur[:b.n_slots]):
            f, gname = b.slot_key[slot]
            g = self.broker.shared.get(f, {}).get(gname)
            if g is not None and g.members:
                g.cursor = (g.cursor + int(occur[slot])) % len(g.members)

    def _consume_one(self, msg, m_row, r_row, o_row, ss_row, sr_row, so_row,
                     words, dev_shared: bool) -> int:
        """Turn one message's RouteResult rows into deliveries."""
        broker = self.broker
        metrics = self.node.metrics
        b = self._built
        n = 0
        matched: list[str] = []
        off = 0
        for fid in m_row:
            if fid < 0:
                continue
            f = b.fid_filter[fid]
            seg = b.seg_len[fid]
            matched.append(f)
            if f in self.dirty_filters or f in self.rich_filters:
                n += broker.dispatch(f, msg)
            else:
                for k in range(off, off + seg):
                    sid = int(r_row[k])
                    if sid < 0:
                        continue
                    if broker._deliver(sid, f, msg,
                                       _unpack_opts(int(o_row[k]))):
                        n += 1
                        metrics.inc("messages.routed.device")
            off += seg

        # filters added since the snapshot: host trie + host dispatch
        if self._delta_filter:
            ids = self.intern.encode_topic(words)
            dol = words[0].startswith("$") if words else False
            for dfid in self._delta_trie.match(ids, dol):
                f = self._delta_filter.get(dfid)
                if f is None:
                    continue
                matched.append(f)
                n += broker.dispatch(f, msg)

        # shared subscriptions
        if dev_shared:
            handled: set[tuple] = set()
            for k, slot in enumerate(ss_row):
                if slot < 0:
                    continue
                f, gname = b.slot_key[slot]
                handled.add((f, gname))
                if (f, gname) in self.dirty_slots:
                    g = broker.shared.get(f, {}).get(gname)
                    if g is not None and g.members and \
                            broker._shared_pick_deliver(gname, f, g, msg):
                        n += 1
                    continue
                sid = int(sr_row[k])
                if sid >= 0 and broker._deliver(
                        sid, f, msg,
                        dict(_unpack_opts(int(so_row[k])), share=gname)):
                    n += 1
                    metrics.inc("messages.routed.device")
            # groups created after the snapshot on matched filters
            for f in matched:
                for gname in self.new_slots_by_filter.get(f, ()):
                    if (f, gname) in handled:
                        continue
                    g = broker.shared.get(f, {}).get(gname)
                    if g is not None and g.members and \
                            broker._shared_pick_deliver(gname, f, g, msg):
                        n += 1
                # delta filters' groups (host dispatch covers them all)
                if f in self._delta_fid_of:
                    for gname, g in broker.shared.get(f, {}).items():
                        if (f, gname) not in handled and g.members and \
                                broker._shared_pick_deliver(gname, f, g, msg):
                            n += 1
        else:
            n += broker._dispatch_shared(msg, matched)

        if broker.cluster:
            n += broker.cluster.forward(msg, matched)
        if n == 0 and not msg.is_sys:
            metrics.inc("messages.dropped")
            metrics.inc("messages.dropped.no_subscribers")
            broker.hooks.run("message.dropped", (msg, "no_subscribers"))
        return n

    def stats(self) -> dict:
        b = self._built
        return {
            "built": b is not None,
            "backend": b.backend if b else None,
            "filters": len(b.fid_filter) if b else 0,
            "shared_slots": b.n_slots if b else 0,
            "churn": self.staleness(),
            "dirty_filters": len(self.dirty_filters),
            "delta_filters": len(self._delta_filter),
        }
